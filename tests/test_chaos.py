"""Chaos soak battery: seeded fault schedules over live serving
traffic, gated on the invariant checker.

The fast deterministic subset runs in tier-1 (seconds); the full
acceptance soak — 200+ ticks, >= 10 injected faults across every
fault family incl. a worker kill and a mid-run checkpoint/restore —
is marked ``slow`` (it is the `make chaos-smoke` / release gate).
A failing soak replays bit-for-bit from its seed.
"""

import dataclasses

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from triton_dist_tpu.models import Engine, ModelConfig, dense
from triton_dist_tpu.resilience import chaos
from triton_dist_tpu.resilience.policy import RetryPolicy
from triton_dist_tpu.serving import DisaggServingEngine, ServingEngine

TINY = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                        intermediate_size=32, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=4,
                        head_dim=8)
CFG = ModelConfig.tiny()


@pytest.fixture(scope="module")
def tiny_factory():
    """Colocated two-role serving over the tiny model on one device —
    the cheap soak target (chunked prefill + local migration + retry +
    failover all reachable)."""
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))

    def factory():
        eng = Engine(TINY, mesh, mode="xla", max_len=32, seed=0)
        return DisaggServingEngine(
            eng, num_slots=2, page=8, prefill_buckets=(4, 8),
            prefix_reuse=True, retry=RetryPolicy(max_attempts=2),
            worker_fail_threshold=2)

    return factory


# ---------------------------------------------------------------------------
# Invariant checker units: a checker that cannot fail gates nothing.
# ---------------------------------------------------------------------------

def _live_engine():
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    eng = Engine(TINY, mesh, mode="xla", max_len=32, seed=0)
    srv = ServingEngine(eng, num_slots=2, page=8, prefix_reuse=True)
    srv.submit([1, 2, 3], max_new_tokens=6)
    srv.submit([4, 5], max_new_tokens=6)
    for _ in range(2):
        srv.step()
    return srv


def test_checker_passes_on_healthy_engine():
    srv = _live_engine()
    chaos.check_invariants(srv)
    srv.run()
    chaos.check_invariants(srv)


def test_checker_catches_leaked_page():
    srv = _live_engine()
    # simulate a leak: a page vanishes from the free list with no ref
    srv.manager._free.pop()
    with pytest.raises(chaos.InvariantViolation, match="LEAKED"):
        chaos.check_invariants(srv)


def test_checker_catches_refcount_drift():
    srv = _live_engine()
    slot = next(iter(srv.manager._slot_pages))
    pid = srv.manager._slot_pages[slot][0]
    srv.manager._refs[pid] += 1
    with pytest.raises(chaos.InvariantViolation, match="refcount"):
        chaos.check_invariants(srv)


def test_checker_catches_mirror_drift():
    srv = _live_engine()
    slot = next(iter(srv.sched.slots))
    srv._lens[slot] += 3
    with pytest.raises(chaos.InvariantViolation, match="mirror"):
        chaos.check_invariants(srv)


def test_checker_catches_staged_published_overlap():
    srv = _live_engine()
    mgr = srv.manager
    slot = next(iter(mgr._slot_pages))
    pid = mgr._slot_pages[slot][0]
    key = next(iter(mgr._prefix)) if mgr._prefix else ("k",)
    mgr._pending_prefix[slot] = [(key, pid)]
    mgr._prefix[key] = pid
    mgr._refs[pid] += 1
    with pytest.raises(chaos.InvariantViolation):
        chaos.check_invariants(srv)


# ---------------------------------------------------------------------------
# Seeded soaks (fast tier-1 subset)
# ---------------------------------------------------------------------------

def test_soak_replays_bit_for_bit(tiny_factory):
    a = chaos.run_soak(tiny_factory, seed=3, ticks=25, n_faults=3)
    b = chaos.run_soak(tiny_factory, seed=3, ticks=25, n_faults=3)

    def sched(rep):
        # Everything but the `at` clock stamp must replay bit-for-bit;
        # `at` rides the engine clock (wall time here — deterministic
        # only under an injected fake clock, see tests/test_obs.py).
        return [dataclasses.astuple(e)[:-1] for e in rep.events]

    assert sched(a) == sched(b)
    assert all(e.at is not None for e in a.events if e.fired)
    assert a.requests == b.requests
    assert a.counters == b.counters


def test_soak_fast_mixed_faults(tiny_factory):
    rep = chaos.run_soak(tiny_factory, seed=7, ticks=60, n_faults=6)
    assert rep.faults_injected == 6
    assert rep.survived_faults == 6
    assert rep.requests["submitted"] > 0
    total = sum(rep.requests[k] for k in ("done", "failed", "timeout"))
    assert total == rep.requests["submitted"], "all terminal"
    assert rep.token_exact_requests == rep.requests["done"]
    assert rep.invariant_checks >= rep.ticks


def test_soak_with_midrun_restore(tiny_factory):
    rep = chaos.run_soak(tiny_factory, seed=7, ticks=60, n_faults=6,
                         restore_at=25)
    assert rep.restored_at == 25
    assert rep.survived_faults == 6
    assert rep.token_exact_requests == rep.requests["done"]


def test_soak_worker_kill_only(tiny_factory):
    """Pin the schedule to the dead-prefill-worker event — failover
    must fire and the run still resolves token-exact."""
    kinds = [("kill_prefill_worker", None, None)]
    rep = chaos.run_soak(tiny_factory, seed=5, ticks=40, n_faults=2,
                         kinds=kinds)
    assert rep.counters["failovers"] >= 1
    assert rep.token_exact_requests == rep.requests["done"]


# One engine per build config for the module: each factory() call
# wraps the SAME engine in a fresh ServingEngine — safe because the
# soak's factory re-invocations are strictly sequential (the restore
# drill overwrites pools/scales wholesale; the oracle runs only after
# the soak srv drained) and engine builds dominate wall clock.
_MK_ENGINES: dict = {}


def _mk_factory(**kw):
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    key = tuple(sorted(kw.items()))
    if key not in _MK_ENGINES:
        cfg = ModelConfig.tiny(vocab_size=128)
        mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
        _MK_ENGINES[key] = MegaKernelEngine(
            cfg, mesh, batch=2, max_len=32, tile_w=16, t_tile=16,
            paged=True, page=16, num_pages=5, **kw)

    def factory():
        return ServingEngine(_MK_ENGINES[key], **(
            {"kv_dtype": kw["kv_dtype"]} if "kv_dtype" in kw else {}))

    return factory


def test_soak_megakernel_with_restore():
    """The converted mk-reject: the chaos soak drives the PERSISTENT
    lane too — seeded decode drops/wedges under MK_FAULT_KINDS, the
    mid-run kill/checkpoint/restore drill through the schema snapshot,
    the extended arena-coherence sweep (region disjointness, scale
    sanity, monotonic counters) after EVERY tick, and survivors
    token-exact vs a fault-free serving oracle."""
    rep = chaos.run_soak(_mk_factory(), seed=3, ticks=30, n_faults=3,
                         kinds=chaos.MK_FAULT_KINDS, restore_at=15,
                         gen_choices=(2, 3), arrival_p=0.4)
    assert rep.faults_injected == 3
    assert rep.restored_at == 15
    assert rep.requests["done"] >= 1
    assert rep.token_exact_requests == rep.requests["done"]
    assert rep.invariant_checks >= 30


def test_soak_megakernel_quantized():
    """Quantized mk soak: the scale-sanity half of the arena sweep
    runs against live int8 pools under decode faults."""
    rep = chaos.run_soak(_mk_factory(kv_dtype="int8"), seed=5,
                         ticks=20, n_faults=2,
                         kinds=chaos.MK_FAULT_KINDS,
                         gen_choices=(2, 3), arrival_p=0.4)
    assert rep.faults_injected == 2
    assert rep.token_exact_requests == rep.requests["done"]


def test_arena_checker_catches_corruption():
    """A checker that cannot fail gates nothing: a clobbered scale
    plane and a backwards counter must raise InvariantViolation."""
    import jax.numpy as jnp

    srv = _mk_factory(kv_dtype="int8")()
    srv.generate([[1, 2, 3]], max_new_tokens=2)
    chaos.check_invariants(srv)               # healthy passes
    good = srv.engine.k_scale
    srv.engine.k_scale = jnp.asarray(good).at[0, 1, 0, 0].set(-1.0)
    with pytest.raises(chaos.InvariantViolation, match="scale"):
        chaos.check_invariants(srv)
    srv.engine.k_scale = good
    chaos.check_invariants(srv)

    moe = ModelConfig.tiny_moe(vocab_size=64, hidden_size=32,
                               num_hidden_layers=2,
                               num_attention_heads=4,
                               num_key_value_heads=2, head_dim=8,
                               num_experts=4, num_experts_per_tok=2,
                               moe_intermediate_size=32)
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    msrv = ServingEngine(MegaKernelEngine(moe, mesh, batch=2,
                                          max_len=32, tile_w=16,
                                          t_tile=16, paged=True,
                                          page=16, num_pages=5))
    msrv.generate([[1, 2]], max_new_tokens=2)
    chaos.check_invariants(msrv)              # seeds the counter sweep
    msrv._mk_counts_sweep = msrv._mk_counts_sweep + 10
    with pytest.raises(chaos.InvariantViolation, match="BACKWARDS"):
        chaos.check_invariants(msrv)


# ---------------------------------------------------------------------------
# The acceptance soak (slow tier): 200+ ticks, >= 10 faults, split
# roles, mid-run kill/restore.
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soak_acceptance_200_ticks_disjoint_roles():
    params = dense.init_params(jax.random.PRNGKey(3), CFG)
    devs = jax.devices()

    def factory():
        pf = Engine(CFG, Mesh(np.array(devs[:2]), ("tp",)),
                    mode="xla", max_len=64, params=params)
        dec = Engine(CFG, Mesh(np.array(devs[2:4]), ("tp",)),
                     mode="xla", max_len=64, params=params)
        return DisaggServingEngine(
            dec, prefill_engine=pf, num_slots=2, page=8,
            prefill_buckets=(4, 16), prefix_reuse=True,
            retry=RetryPolicy(max_attempts=2),
            worker_fail_threshold=2)

    rep = chaos.run_soak(factory, seed=17, ticks=200, n_faults=12,
                         restore_at=90)
    assert rep.faults_injected >= 10
    assert rep.survived_faults >= 10
    assert rep.restored_at == 90
    total = sum(rep.requests[k] for k in ("done", "failed", "timeout"))
    assert total == rep.requests["submitted"]
    assert rep.token_exact_requests == rep.requests["done"]
    assert rep.invariant_checks >= 200
