"""Fused Ulysses GEMM+A2A tests.

Oracle pattern: the unfused pipeline (projection → ``pre_attn_a2a`` /
``post_attn_a2a`` → projection) from ``ops/ulysses.py``, mirroring the
reference's ``test_sp_ulysess_qkv_gemm_all2all.py`` torch oracles.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.ulysses import pre_attn_a2a, post_attn_a2a
from triton_dist_tpu.ops.ulysses_fused import (
    create_ulysses_fused_context, qkv_gemm_a2a, o_a2a_gemm,
    group_qkv_columns, group_o_rows, ulysses_attn_fused,
)
from triton_dist_tpu.utils.testing import spmd, assert_allclose

N = 8
S_LOC = 8     # sequence rows per rank
D = 32        # model dim
HD = 4        # head dim
H = 16        # q heads (2 per rank)
KV = 8        # kv heads (1 per rank)


def _rand(shape, seed):
    return jax.random.normal(jax.random.PRNGKey(seed), shape)


def _per_rank(fn, mesh, in_specs, out_rank_axis="tp"):
    """Run fn per-rank and collect each rank's output along axis 0."""
    def wrapped(*args):
        return fn(*args)[None]
    return spmd(mesh, wrapped, in_specs, P(out_rank_axis, *([None] * 3)))


def test_qkv_gemm_a2a_vs_oracle(tp8_mesh, tp8_ctx):
    ctx = create_ulysses_fused_context(tp8_ctx, axis="tp", block_m=8,
                                       block_n=8)
    cols = (H + 2 * KV) * HD // N
    x = _rand((N * S_LOC, D), 0)
    w = _rand((N, D, cols), 1) * D ** -0.5

    fused = _per_rank(lambda xs, ws: qkv_gemm_a2a(xs, ws, ctx),
                      tp8_mesh,
                      (P("tp", None), P(None, None, None)))
    got = np.asarray(fused(x, w))          # (n_me, n_src, S_loc, cols)

    # Oracle: rank me's buffer[src] = x_src @ w[me].
    xs = np.asarray(x).reshape(N, S_LOC, D)
    wn = np.asarray(w)
    for me in range(N):
        want = np.einsum("nsd,dc->nsc", xs, wn[me])
        np.testing.assert_allclose(got[me], want, rtol=2e-4, atol=2e-4)


def test_o_a2a_gemm_vs_oracle(tp8_mesh, tp8_ctx):
    ctx = create_ulysses_fused_context(tp8_ctx, axis="tp", block_m=8,
                                       block_n=16)
    rows_loc = H * HD // N
    o = _rand((N, N * S_LOC, rows_loc), 2)  # per-rank head activations
    w = _rand((N, rows_loc, D), 3) * (H * HD) ** -0.5

    def run(o_all, ws):
        me = jax.lax.axis_index("tp")
        return o_a2a_gemm(o_all[me], ws, ctx)

    f = spmd(tp8_mesh, run, (P(None, None, None), P(None, None, None)),
             P("tp", None))
    got = np.asarray(f(o, w))               # (N·S_loc, D) rows by rank

    # Oracle: out rows of rank r = Σ_src o[src, r's seq rows] @ w[src].
    on, wn = np.asarray(o), np.asarray(w)
    want = np.zeros((N * S_LOC, D), np.float32)
    for r in range(N):
        rows = slice(r * S_LOC, (r + 1) * S_LOC)
        want[rows] = sum(on[src, rows] @ wn[src] for src in range(N))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_ulysses_attn_fused_vs_unfused(tp8_mesh, tp8_ctx):
    """End-to-end block equals the serial projection→A2A→attention→
    A2A→projection pipeline."""
    from triton_dist_tpu.layers.tp_attn import sdpa

    ctx = create_ulysses_fused_context(tp8_ctx, axis="tp", block_m=8,
                                       block_n=8)
    x = _rand((N * S_LOC, D), 4)
    w_qkv = _rand((D, (H + 2 * KV) * HD), 5) * D ** -0.5
    w_o = _rand((H * HD, D), 6) * (H * HD) ** -0.5
    wq_g = group_qkv_columns(w_qkv, n=N, num_heads=H, num_kv_heads=KV,
                             head_dim=HD)
    wo_g = group_o_rows(w_o, n=N, num_heads=H, head_dim=HD)

    f = spmd(tp8_mesh,
             lambda xs: ulysses_attn_fused(
                 xs, wq_g, wo_g, ctx, num_heads=H, num_kv_heads=KV,
                 head_dim=HD, causal=True),
             P("tp", None), P("tp", None))
    got = np.asarray(f(x))

    # Unfused oracle (single host, no sharding).
    qkv = np.asarray(x) @ np.asarray(w_qkv)
    s = N * S_LOC
    q = qkv[:, :H * HD].reshape(s, H, HD)
    k = qkv[:, H * HD:(H + KV) * HD].reshape(s, KV, HD)
    v = qkv[:, (H + KV) * HD:].reshape(s, KV, HD)
    o = np.asarray(sdpa(jnp.asarray(q)[None], jnp.asarray(k)[None],
                        jnp.asarray(v)[None], causal=True)[0])
    want = o.reshape(s, H * HD) @ np.asarray(w_o)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)
