"""Serving subsystem battery: block manager, continuous batching,
token-exactness vs ``Engine.serve`` under churn, backpressure,
deadlines, and the CommTimeoutError containment path.

Everything is seeded and clock-injected — no wall-clock anywhere; the
randomized arrival schedule is a fixed RandomState so the admission /
EOS-recycle interleavings are reproducible.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu.models import Engine, ModelConfig
from triton_dist_tpu.serving import (
    BlockManager, BlockTableOverflowError, OutOfPagesError, PagedKVCache,
    QueueFullError, Request, ServingEngine,
)
from triton_dist_tpu.resilience.watchdog import CommTimeoutError

TP = 4
CFG = ModelConfig.tiny()
MAX_LEN = 64
PAGE = 8
VOCAB = CFG.vocab_size


@pytest.fixture(scope="module")
def engine():
    mesh = Mesh(np.array(jax.devices()[:TP]), ("tp",))
    return Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=3)


def _baseline(engine, prompt, gen_len, eos_id=None):
    """Sequential oracle: Engine.serve on the tiled prompt (row 0),
    truncated at EOS inclusively — the per-request ground truth."""
    ids = jnp.asarray(np.tile(np.asarray([prompt], np.int32), (TP, 1)))
    toks = np.asarray(engine.serve(ids, gen_len=gen_len))[0].tolist()
    if eos_id is not None and eos_id in toks:
        toks = toks[:toks.index(eos_id) + 1]
    return toks


# ---------------------------------------------------------------------------
# block manager (pure host logic)
# ---------------------------------------------------------------------------

def test_block_manager_alloc_append_free():
    m = BlockManager(num_pages=6, page=4, p_max=4)
    pages = m.alloc_prefill(0, list(range(6)))   # 6 tokens -> 2 pages
    assert len(pages) == 2 and 0 not in pages    # scratch reserved
    # appends fill page 2 (tokens 6, 7), then a third page at token 8.
    assert m.append(0) is None and m.append(0) is None
    new = m.append(0)
    assert new is not None and new not in pages
    frag = m.fragmentation()
    assert frag["used_pages"] == 3 and frag["free_pages"] == 2
    assert 0.0 < frag["utilization"] <= 1.0
    m.free_slot(0)
    assert m.fragmentation()["free_pages"] == 5


def test_block_manager_backpressure_and_rollback():
    m = BlockManager(num_pages=3, page=4, p_max=4)   # 2 usable pages
    m.alloc_prefill(0, list(range(8)))               # takes both
    with pytest.raises(OutOfPagesError):
        m.alloc_prefill(1, [1, 2, 3])
    # failed alloc must not leak pages
    m.free_slot(0)
    assert m.fragmentation()["free_pages"] == 2


def test_block_manager_row_overflow():
    m = BlockManager(num_pages=8, page=4, p_max=2)
    with pytest.raises(BlockTableOverflowError):
        m.alloc_prefill(0, list(range(12)))          # 3 pages > p_max
    m.alloc_prefill(1, list(range(8)))               # fills the row
    with pytest.raises(BlockTableOverflowError):
        m.append(1)                                  # token 9 needs row 3


def test_block_manager_prefix_reuse():
    m = BlockManager(num_pages=10, page=4, p_max=6, prefix_reuse=True)
    p0 = m.alloc_prefill(0, [1, 2, 3, 4, 5, 6, 7, 8, 9])  # 2 full + 1
    # Two-phase publication: until the content-resident commit, a
    # same-prefix alloc must MISS (the pages hold no KV yet).
    probe = m.alloc_prefill(7, [1, 2, 3, 4, 5, 6, 7, 8])
    assert probe[:2] != p0[:2], "uncommitted prefix pages were shared"
    m.free_slot(7)
    m.commit_prefix(0)
    p1 = m.alloc_prefill(1, [1, 2, 3, 4, 5, 6, 7, 8, 42])
    m.commit_prefix(1)
    assert p0[:2] == p1[:2], "full prefix pages must be shared"
    assert p0[2] != p1[2], "ragged tails stay private"
    assert m.stats["prefix_hits"] == 2
    # different first page -> no sharing
    p2 = m.alloc_prefill(2, [9, 9, 9, 9, 5, 6, 7, 8])
    assert p2[0] not in (p0[0],)
    # freeing both sharers keeps prefix pages cached until eviction
    m.free_slot(0)
    m.free_slot(1)
    before = m.fragmentation()["prefix_pages"]
    assert before >= 2
    # exhaust the pool: eviction reclaims unreferenced prefix pages
    got = m.alloc_prefill(3, list(range(100, 124)))  # 6 pages
    assert len(got) == 6
    assert m.stats["evictions"] >= 1


def test_paged_cache_append_and_gather():
    """PagedKVCache.append_decode + dense_layer against a hand scatter."""
    rng = np.random.RandomState(0)
    cache = PagedKVCache.empty(1, 5, 4, 2, 3, num_slots=2, p_max=2)
    tbl = np.array([[1, 2], [0, 0]], np.int32)   # parked row = scratch
    lens = np.array([5, 0], np.int32)    # slot0 mid page 2; slot1 parked
    live = np.array([1, 0], np.int32)
    cache = dataclasses.replace(
        cache, block_table=jnp.asarray(tbl), lens=jnp.asarray(lens),
        live=jnp.asarray(live))
    k = rng.randn(2, 1, 2, 3).astype(np.float32)
    v = rng.randn(2, 1, 2, 3).astype(np.float32)
    cache = cache.append_decode(0, jnp.asarray(k), jnp.asarray(v))
    kp = np.asarray(cache.k_pages)
    # slot0: position 5 -> row 1 (page id 2), offset 1
    np.testing.assert_array_equal(kp[0, 2, :, 1, :], k[0, 0])
    # slot1 parked: its append landed in the scratch page (0), off 0
    np.testing.assert_array_equal(kp[0, 0, :, 0, :], k[1, 0])
    kd, _ = cache.dense_layer(0)
    np.testing.assert_array_equal(np.asarray(kd)[0, 5], k[0, 0])
    cache = cache.advance()
    np.testing.assert_array_equal(np.asarray(cache.lens), [6, 0])


# ---------------------------------------------------------------------------
# continuous batching vs the sequential baseline
# ---------------------------------------------------------------------------

def test_continuous_token_exact_random_churn(engine):
    """Admission → prefill → joined decode → EOS recycle under a
    seeded randomized arrival schedule: every request's tokens equal
    its solo Engine.serve run."""
    rng = np.random.RandomState(7)
    reqs = []
    for _ in range(6):
        plen = int(rng.randint(1, 9))
        prompt = [int(t) for t in rng.randint(0, VOCAB, plen)]
        gen = int(rng.randint(1, 7))
        reqs.append((prompt, gen))
    # Derive an EOS for two requests from their own baseline output so
    # early-stop (slot recycle mid-run) actually triggers.
    base_plain = [_baseline(engine, p, g) for p, g in reqs]
    eos = [None] * len(reqs)
    for i in (1, 4):
        toks = base_plain[i]
        if len(toks) > 1:
            eos[i] = toks[len(toks) // 2]
    want = [_baseline(engine, p, g, e)
            for (p, g), e in zip(reqs, eos)]

    srv = ServingEngine(engine, num_slots=2, page=PAGE)
    handles = []
    pending = list(zip(reqs, eos))
    rng2 = np.random.RandomState(8)
    while pending or not srv.sched.idle:
        # randomized arrivals: 0-2 submissions per tick
        for _ in range(int(rng2.randint(0, 3))):
            if pending:
                (prompt, gen), e = pending.pop(0)
                handles.append(srv.submit(prompt, max_new_tokens=gen,
                                          eos_id=e))
        srv.step()
    assert [h.tokens for h in handles] == want
    assert all(h.status == "done" for h in handles)
    st = srv.stats()
    assert st["completed"] == len(reqs)
    assert st["pool"]["used_pages"] == 0, "all pages recycled"


def test_static_policy_gang_batching(engine):
    """policy='static' is still token-exact but needs more decode
    dispatches than continuous batching on a skewed workload — the
    bench's serving_tokens_per_s comparison in miniature."""
    prompts = [[1, 2, 3], [4, 5], [6], [7, 8]]
    gens = [2, 6, 2, 6]
    want = [_baseline(engine, p, g) for p, g in zip(prompts, gens)]

    def run(policy):
        srv = ServingEngine(engine, num_slots=2, page=PAGE,
                            policy=policy)
        hs = [srv.submit(p, max_new_tokens=g)
              for p, g in zip(prompts, gens)]
        srv.run()
        return [h.tokens for h in hs], srv.stats()["decode_dispatches"]

    out_c, steps_c = run("continuous")
    out_s, steps_s = run("static")
    assert out_c == want and out_s == want
    assert steps_c <= steps_s


def test_admission_backpressure(engine):
    srv = ServingEngine(engine, num_slots=1, page=PAGE, max_queue=2)
    srv.submit([1, 2], max_new_tokens=2)
    srv.submit([3, 4], max_new_tokens=2)
    with pytest.raises(QueueFullError):
        srv.submit([5, 6], max_new_tokens=2)
    assert srv.stats()["rejected"] == 1
    srv.run()
    assert srv.stats()["completed"] == 2


def test_out_of_pages_stalls_then_completes(engine):
    """An undersized pool stalls admission (requeue, not failure) until
    a finishing request frees pages."""
    # ONE usable page + scratch: the second request must wait for the
    # first to finish and free it.
    srv = ServingEngine(engine, num_slots=2, page=PAGE, num_pages=2)
    h1 = srv.submit([1, 2, 3], max_new_tokens=3)
    h2 = srv.submit([4, 5, 6], max_new_tokens=3)
    srv.run()
    assert h1.status == "done" and h2.status == "done"
    assert srv.stats()["admit_stalls"] >= 1
    want = [_baseline(engine, [1, 2, 3], 3),
            _baseline(engine, [4, 5, 6], 3)]
    assert [h1.tokens, h2.tokens] == want


def test_mid_decode_preemption_token_exact(engine):
    """Pool exhaustion while GROWING a running request preempts it
    (pages freed, requeued at the head, resumed via re-prefill of
    prompt + generated-so-far) — never crashes the loop, and the
    preempted request's final tokens still match its solo baseline."""
    prompts = [[1, 2, 3, 4, 5, 6], [7, 8, 9, 10, 11, 12]]
    want = [_baseline(engine, p, 4) for p in prompts]
    # 2 usable pages: one per slot at prefill; the first page-boundary
    # crossing (position 8) finds the pool dry.
    srv = ServingEngine(engine, num_slots=2, page=PAGE, num_pages=3)
    hs = [srv.submit(p, max_new_tokens=4) for p in prompts]
    srv.run()
    assert [h.status for h in hs] == ["done", "done"]
    assert [h.tokens for h in hs] == want
    assert srv.stats()["preemptions"] >= 1


def test_pool_never_satisfiable_fails_fast(engine):
    """A request whose pages can NEVER be freed by anyone (empty
    server, pool smaller than the prompt) fails instead of spinning."""
    srv = ServingEngine(engine, num_slots=2, page=PAGE, num_pages=2)
    h = srv.submit(list(range(PAGE + 1)), max_new_tokens=2)  # 2 pages
    srv.run()
    assert h.status == "failed"
    assert isinstance(h.error, OutOfPagesError)


def test_capacity_validation(engine):
    srv = ServingEngine(engine, num_slots=1, page=PAGE)
    with pytest.raises(ValueError, match="exceeds capacity"):
        srv.submit(list(range(60)), max_new_tokens=10)
    with pytest.raises(ValueError, match="empty"):
        srv.submit([], max_new_tokens=2)


def test_streaming_callbacks(engine):
    seen = []
    srv = ServingEngine(engine, num_slots=1, page=PAGE)
    h = srv.submit([1, 2, 3], max_new_tokens=4,
                   stream_cb=lambda tok, hh: seen.append(
                       (tok, len(hh.tokens))))
    srv.run()
    assert [t for t, _ in seen] == h.tokens
    # streamed as generated: callback i fires when i+1 tokens exist
    assert [n for _, n in seen] == [1, 2, 3, 4]


def test_deadline_fails_one_request(engine):
    """A deadline miss (injected clock) fails that request only; the
    survivor's tokens stay exact."""
    clock = [0.0]
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        clock=lambda: clock[0])
    slow = srv.submit([1, 2], max_new_tokens=8, deadline=3.0)
    fast = srv.submit([3, 4], max_new_tokens=8)
    srv.step()                    # both admitted, first decode
    clock[0] = 5.0                # past slow's deadline
    srv.run()
    assert slow.status == "timeout"
    assert isinstance(slow.error, TimeoutError)
    assert fast.status == "done"
    assert fast.tokens == _baseline(engine, [3, 4], 8)
    assert srv.stats()["timed_out"] == 1


def test_comm_timeout_fails_victim_not_server(engine):
    """A hung collective (CommTimeoutError on the shared dispatch)
    fails the scheduler's victim; the server keeps serving and the
    survivor stays token-exact."""
    srv = ServingEngine(engine, num_slots=2, page=PAGE)
    eldest = srv.submit([1, 2, 3], max_new_tokens=6)
    srv.step()                    # eldest admitted + first decode
    younger = srv.submit([4, 5], max_new_tokens=4)
    real = srv._decode
    state = {"armed": False}

    def flaky(*a, **kw):
        if state["armed"]:
            state["armed"] = False
            raise CommTimeoutError(op="serving.decode", rank=0,
                                   timeout_s=0.1, progress=None)
        return real(*a, **kw)

    srv._decode = flaky
    srv.step()                    # younger admitted this tick
    state["armed"] = True
    srv.step()                    # wedged dispatch -> eldest fails
    srv.run()
    assert eldest.status == "timeout"
    assert isinstance(eldest.error, CommTimeoutError)
    assert younger.status == "done"
    assert younger.tokens == _baseline(engine, [4, 5], 4)
    assert srv.stats()["comm_timeouts"] == 1


def test_prefill_timeout_fails_admitting_request_only(engine):
    """A wedged PREFILL dispatch fails the admitting request (slot and
    pages released — no leaked half-admitted state); requests already
    decoding are untouched and stay exact."""
    srv = ServingEngine(engine, num_slots=2, page=PAGE)
    ok = srv.submit([1, 2, 3], max_new_tokens=5)
    srv.step()                    # ok admitted + decoding
    real = srv.engine.prefill
    state = {"armed": True}

    def flaky(ids):
        if state["armed"]:
            state["armed"] = False
            raise CommTimeoutError(op="engine.prefill", rank=0,
                                   timeout_s=0.1, progress=None)
        return real(ids)

    srv.engine.prefill = flaky
    doomed = srv.submit([4, 5], max_new_tokens=3)
    try:
        srv.run()
    finally:
        srv.engine.prefill = real
    assert doomed.status == "timeout"
    assert isinstance(doomed.error, CommTimeoutError)
    assert ok.status == "done"
    assert ok.tokens == _baseline(engine, [1, 2, 3], 5)
    assert srv.stats()["pool"]["used_pages"] == 0, "pages leaked"
    assert not srv.sched.slots, "slot leaked"


def test_no_recompile_after_warmup(engine):
    """Fixed decode-batch shape: the decode jit cache stops growing
    after warmup, over arrivals, EOS recycles, and parked slots."""
    srv = ServingEngine(engine, num_slots=2, page=PAGE)
    srv.generate([[1, 2, 3], [4, 5]], max_new_tokens=3)   # warmup
    warm = srv.decode_cache_size()
    rng = np.random.RandomState(21)
    for _ in range(5):
        plen = int(rng.randint(1, 8))
        srv.submit([int(t) for t in rng.randint(0, VOCAB, plen)],
                   max_new_tokens=int(rng.randint(1, 5)))
        srv.step()
    srv.run()
    assert srv.decode_cache_size() == warm, (
        "decode dispatch re-specialized after warmup")


def test_kernel_attn_impl_matches_baseline(engine):
    """attn_impl='kernel' (the in-kernel paged flash decode, axis=None
    local form) greedy-matches the sequential baseline too."""
    prompts = [[1, 2, 3], [7, 8]]
    want = [_baseline(engine, p, 3) for p in prompts]
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        attn_impl="kernel")
    assert srv.generate(prompts, max_new_tokens=3) == want


def test_prefix_reuse_serving(engine):
    """Shared page-aligned prompt prefixes: fewer pages, same tokens."""
    shared = list(range(1, 17))            # two full pages at PAGE=8
    p1 = shared + [30, 31]
    p2 = shared + [40]
    want = [_baseline(engine, p1, 3), _baseline(engine, p2, 3)]
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        prefix_reuse=True)
    out = srv.generate([p1, p2], max_new_tokens=3)
    assert out == want
    assert srv.manager.stats["prefix_hits"] >= 2


# ---------------------------------------------------------------------------
# megakernel path (prefill lane + live slot mask)
# ---------------------------------------------------------------------------

def test_megakernel_paged_serving_token_exact():
    """PAGED megakernel serving: the manager's block table is installed
    on the engine each tick (parked rows hit the scratch page), and
    staggered requests through allocator-assigned pages match solo runs
    on the identity-table engine."""
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    cfg = ModelConfig.tiny(vocab_size=128)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    kw = dict(batch=2, max_len=32, tile_w=16, t_tile=16, paged=True,
              page=16)
    prompts = [[5, 6, 7], [3, 4]]
    gen = 3

    def solo(prompt):
        e = MegaKernelEngine(cfg, mesh, **kw)
        tiled = jnp.asarray(np.tile(np.asarray([prompt], np.int32),
                                    (2, 1)))
        seed = e.prefill_chain(tiled)
        return np.asarray(e.generate(
            seed, steps=gen, start_pos=len(prompt) - 1))[0].tolist()

    want = [solo(p) for p in prompts]
    mk = MegaKernelEngine(cfg, mesh, num_pages=2 * 2 + 1, **kw)
    srv = ServingEngine(mk)
    assert srv.manager is not None
    h0 = srv.submit(prompts[0], max_new_tokens=gen)
    srv.step()                       # slot 0 mid-prefill-lane
    # The allocator's table (slot 0 -> a manager page, parked slot 1 ->
    # scratch row of zeros) must actually be installed on the engine —
    # NOT its construction-time identity table.
    installed = np.asarray(mk.block_table).reshape(2, -1)
    assert installed[0, 0] != 0, "slot 0 should map to a manager page"
    np.testing.assert_array_equal(installed[1], 0)   # parked -> scratch
    h1 = srv.submit(prompts[1], max_new_tokens=gen)
    srv.run()
    assert [h0.tokens, h1.tokens] == want
    assert srv.stats()["pool"]["used_pages"] == 0


def test_megakernel_hybrid_timeout_fails_all_in_flight():
    """Hybrid GDN megakernel: the recurrent state cannot be rewound, so
    a decode timeout fails EVERY in-flight request; fresh requests
    (slots reset) still serve fine afterwards."""
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    cfg = ModelConfig.tiny_next(vocab_size=128, num_key_value_heads=4,
                                full_attn_interval=2)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    mk = MegaKernelEngine(cfg, mesh, batch=2, max_len=16,
                          tile_w=16, t_tile=16)
    srv = ServingEngine(mk)
    a = srv.submit([5, 6], max_new_tokens=4)
    b = srv.submit([7], max_new_tokens=4)
    srv.step()
    real = mk.decode_step
    state = {"armed": True}

    def flaky(toks, lens):
        if state["armed"]:
            state["armed"] = False
            raise CommTimeoutError(op="megakernel.decode_step", rank=0,
                                   timeout_s=0.1, progress=None)
        return real(toks, lens)

    mk.decode_step = flaky
    srv.step()
    mk.decode_step = real
    assert a.status == "timeout" and b.status == "timeout"
    fresh = srv.submit([9, 10], max_new_tokens=2)
    srv.run()
    assert fresh.status == "done" and len(fresh.tokens) == 2


def test_megakernel_serving_token_exact():
    """Continuous batching over the persistent megakernel: staggered
    requests through the prefill lane match solo runs."""
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    cfg = ModelConfig.tiny(vocab_size=128)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    prompts = [[5, 6, 7], [3], [11, 12]]
    gen = 3

    def solo(prompt):
        e = MegaKernelEngine(cfg, mesh, batch=2, max_len=16,
                             tile_w=16, t_tile=16)
        tiled = jnp.asarray(np.tile(np.asarray([prompt], np.int32),
                                    (2, 1)))
        seed = e.prefill_chain(tiled)
        return np.asarray(e.generate(
            seed, steps=gen, start_pos=len(prompt) - 1))[0].tolist()

    want = [solo(p) for p in prompts]
    mk = MegaKernelEngine(cfg, mesh, batch=2, max_len=16,
                          tile_w=16, t_tile=16)
    srv = ServingEngine(mk)
    h0 = srv.submit(prompts[0], max_new_tokens=gen)
    srv.step()                       # slot 0 mid-prefill-lane
    h1 = srv.submit(prompts[1], max_new_tokens=gen)
    h2 = srv.submit(prompts[2], max_new_tokens=gen)
    srv.run()
    assert [h0.tokens, h1.tokens, h2.tokens] == want
