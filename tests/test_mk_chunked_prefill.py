"""Megakernel chunked-prefill battery.

The WRITE_KV_CHUNK/ATTN_CHUNK task pair replaces the one-token-per-tick
megakernel prefill lane with bucketed fixed-shape chunk launches — the
mk lane's half of ROADMAP Open item 1's chunked-prefill contract.
Everything here is token-exact three ways: chunked mk serving vs the
one-token mk lane, vs the layer ``Engine.serve`` oracle on shared
params, and (quantized) across kv_dtypes between the two mk lanes. The
jit-cache gates mirror tests/test_disagg_serving.py's layer-path ones:
chunk steps bounded by the bucket count, decode never re-specializing
across chunked admissions.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu.models import Engine, ModelConfig
from triton_dist_tpu.serving import ServingEngine

# The bench micro config: interpret-mode dispatch cost scales with
# layers x heads, and this battery builds ~8 engine variants — the
# full tiny config would eat the tier-1 wall-clock budget by itself.
CFG = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                       intermediate_size=32, num_hidden_layers=2,
                       num_attention_heads=4, num_key_value_heads=2,
                       head_dim=8)
VOCAB = CFG.vocab_size
BUCKETS = (4, 16)

# One megakernel engine per build config for the whole module — engine
# builds dominate wall clock, and reuse is the serving layer's
# slot-recycling contract (positions rewrite, lengths mask).
_MK_CACHE: dict = {}


def _mk_engine(**kw):
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    key = tuple(sorted(kw.items()))
    if key not in _MK_CACHE:
        mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
        base = dict(batch=2, max_len=64, tile_w=16, t_tile=16,
                    paged=True, page=16, num_pages=9,
                    keep_params=True)
        base.update(kw)
        _MK_CACHE[key] = MegaKernelEngine(CFG, mesh, **base)
    return _MK_CACHE[key]


def _onetok_tokens(prompts, gen, **kw):
    """Oracle A: the SAME engine shape served through the one-token
    prefill lane (no prefill_buckets)."""
    return ServingEngine(_mk_engine(**kw),
                         **{k: v for k, v in kw.items()
                            if k in ("kv_dtype", "spec_k")}).generate(
        prompts, max_new_tokens=gen)


# ---------------------------------------------------------------------------
# token exactness at the bucket edges
# ---------------------------------------------------------------------------

def test_mk_chunked_token_exact_bucket_edges_vs_lane_and_layer():
    """Prompt lengths straddling every bucket edge (b-1 / b / b+1):
    chunked mk serving streams the SAME tokens as the one-token mk
    lane AND as the layer ``Engine.serve`` oracle on the mk engine's
    own params — chunk boundaries, padding rows, and the sign-encoded
    position codes are all invisible in the tokens."""
    lens = sorted({max(b + d, 1) for b in BUCKETS for d in (-1, 0, 1)})
    prompts = [[int(t) for t in
                np.random.RandomState(n).randint(1, VOCAB, n)]
               for n in lens]
    gen = 4
    want = _onetok_tokens(prompts, gen)

    mk = _mk_engine(prefill_buckets=BUCKETS)
    srv = ServingEngine(mk, prefill_buckets=BUCKETS)
    got = srv.generate(prompts, max_new_tokens=gen)
    assert got == want, "chunked lane diverged from the one-token lane"

    # Layer-path oracle on the same weights: Engine.serve end to end.
    params = jax.tree.map(np.asarray,
                          _mk_engine().params)
    e2 = Engine(CFG, mk.mesh, mode="xla", max_len=64, params=params)
    for p, w in zip(prompts, want):
        ids = np.asarray([p], np.int32)
        ref = np.asarray(e2.serve(ids, gen_len=gen))[0].tolist()
        assert w == ref, "mk lanes diverged from Engine.serve"

    st = srv.stats()
    assert st["prefill_chunks"] > 0
    assert st["mk_chunked_prefill"] == list(BUCKETS)
    assert st["prefill_buckets"] == list(BUCKETS)


@pytest.mark.slow  # ~100s interpret-mode; mkchunk-smoke runs it unfiltered
def test_mk_chunked_quantized_writes_token_agree():
    """int8 / fp8 fused quantize-on-write through WRITE_KV_CHUNK: the
    chunked lane agrees token-for-token with the one-token lane at the
    SAME kv_dtype (both lanes quantize through the same page-start
    scale reset), at bucket-edge lengths covering ragged chunk
    tails."""
    prompts = [[int(t) for t in
                np.random.RandomState(7).randint(1, VOCAB, 17)],
               [int(t) for t in
                np.random.RandomState(8).randint(1, VOCAB, 15)]]
    for kvd in ("int8", "fp8"):
        want = _onetok_tokens(prompts, 4, kv_dtype=kvd)
        srv = ServingEngine(
            _mk_engine(prefill_buckets=BUCKETS, kv_dtype=kvd),
            kv_dtype=kvd, prefill_buckets=BUCKETS)
        assert srv.generate(prompts, max_new_tokens=4) == want, (
            f"{kvd} chunked lane diverged from the one-token lane")


# ---------------------------------------------------------------------------
# prefix reuse: resident pages attend-only, never re-blitted
# ---------------------------------------------------------------------------

def test_mk_chunked_prefix_reuse_never_reblits_resident_pages():
    """Chunked mk × prefix-reuse: the second sharer's chunk stream
    starts past the resident prefix (fewer chunks), the shared pages'
    POOL BYTES are untouched by its prefill (attend-only codes — the
    kernel's write is masked), and tokens stay exact."""
    shared = [int(t) for t in
              np.random.RandomState(3).randint(1, VOCAB, 32)]
    p1, p2 = shared + [30, 31], shared + [40]
    want = _onetok_tokens([p1, p2], 3)

    mk = _mk_engine(prefill_buckets=BUCKETS)
    srv = ServingEngine(mk, prefill_buckets=BUCKETS, prefix_reuse=True)
    h1 = srv.submit(p1, max_new_tokens=3)
    for _ in range(4):
        srv.step()                   # p1 fully prefilled (16+16+4)
    h2 = srv.submit(p2, max_new_tokens=3)    # while h1 still decodes
    pool_before = np.asarray(mk.k_cache)
    srv.step()
    assert srv.manager.prefix_hits(h2.slot) == 2, (
        "second sharer must hit both full prefix pages")
    # The shared pages' bytes are bit-identical across h2's admission
    # chunk: resident positions ride attend-only (enc <= -2) codes, so
    # WRITE_KV_CHUNK never stores to them.
    table = np.asarray(mk.block_table).reshape(srv.num_slots, -1)
    for pid in table[h2.slot][:2]:
        np.testing.assert_array_equal(
            np.asarray(mk.k_cache)[:, int(pid)],
            pool_before[:, int(pid)],
            err_msg="resident prefix page re-blitted by a chunk write")
    srv.run()
    assert [h1.tokens, h2.tokens] == want
    # h2 computed only its non-shared tail: one bucket-4 chunk at the
    # first non-resident position, vs h1's full 16+16+4 stream.
    assert h1.chunks == [(0, 16, 16), (16, 16, 16), (32, 4, 2)]
    assert h2.chunks == [(32, 4, 1)]


# ---------------------------------------------------------------------------
# speculation composes on chunked admission
# ---------------------------------------------------------------------------

@pytest.mark.slow  # ~30s interpret-mode; mkchunk-smoke runs it unfiltered
def test_mk_chunked_spec_composes_token_exact():
    """spec_k on top of chunked admission: prompts enter through the
    chunk task pair, then decode through Q-block verification — tokens
    exactly the plain one-token-lane run's, with > 1 tokens per
    dispatch on the repetitive trace and the sampled-fallback counter
    surfacing in stats()."""
    rep = [[1, 2, 3, 1, 2, 3, 1, 2] * 2, [7, 8, 7, 8, 7, 8] * 2]
    want = _onetok_tokens(rep, 12)
    srv = ServingEngine(
        _mk_engine(prefill_buckets=BUCKETS, spec_k=2,
                   schedule="dynamic"),
        spec_k=2, prefill_buckets=BUCKETS)
    assert srv.generate(rep, max_new_tokens=12) == want
    st = srv.stats()
    assert st["spec"]["tokens_per_dispatch"] > 1.0, st["spec"]
    assert st["prefill_chunks"] > 0
    assert st["spec"]["sampled_fallbacks"] == 0
    assert st["spec_sampled_fallbacks"] == 0

    # A sampled request rides the degenerate repeat-draft (one commit
    # per dispatch) and the fallback counter records each one.
    srv.generate([[5, 6, 7]], max_new_tokens=3, temperature=0.9,
                 seed=11)
    st = srv.stats()
    assert st["spec_sampled_fallbacks"] > 0
    assert st["spec"]["sampled_fallbacks"] == (
        st["spec_sampled_fallbacks"])


# ---------------------------------------------------------------------------
# jit-cache bounds: buckets bound prefill; decode never re-specializes
# ---------------------------------------------------------------------------

def test_mk_chunked_jit_caches_bounded():
    """After warmup over the buckets, UNSEEN prompt lengths cause zero
    new chunk-step or decode compilations: the chunk jit caches stay
    bounded by the bucket count (the engine gates this inline after
    every dispatch) and the decode dispatch is untouched by chunked
    admission."""
    srv = ServingEngine(_mk_engine(prefill_buckets=BUCKETS),
                        prefill_buckets=BUCKETS)
    rng = np.random.RandomState(11)
    srv.generate([[1, 2, 3], list(range(1, 21))], max_new_tokens=2)
    pre, dec = srv.prefill_cache_size(), srv.decode_cache_size()
    assert 0 < pre <= len(BUCKETS)
    for n in (2, 6, 9, 13, 19, 23):     # unseen lengths + a resume mix
        srv.submit([int(t) for t in rng.randint(1, VOCAB, n)],
                   max_new_tokens=2)
        srv.step()
    srv.run()
    assert srv.prefill_cache_size() == pre, "chunk step re-specialized"
    assert srv.decode_cache_size() == dec, "decode re-specialized"
    st = srv.stats()
    assert st["prefill_cache_size"] == pre


# ---------------------------------------------------------------------------
# knob validation + the arena-tier rejects
# ---------------------------------------------------------------------------

def test_mk_chunked_knob_validation():
    """prefill_buckets is an ENGINE knob on the mk lane (the chunk
    task pair is compiled at engine construction): serving/engine
    mismatch in EITHER direction, non-paged builds, and unpadded
    chunk lengths all fail loudly."""
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    with pytest.raises(ValueError, match="prefill_buckets mismatch"):
        ServingEngine(_mk_engine(), prefill_buckets=BUCKETS)
    with pytest.raises(ValueError, match="prefill_buckets mismatch"):
        ServingEngine(_mk_engine(prefill_buckets=BUCKETS))
    with pytest.raises(ValueError, match="paged"):
        MegaKernelEngine(CFG, mesh, batch=2, max_len=32, tile_w=16,
                         t_tile=16, prefill_buckets=(4,))
    eng = _mk_engine(prefill_buckets=BUCKETS)
    with pytest.raises(ValueError, match="no chunk step for bucket"):
        eng.prefill_chunk(np.zeros(5, np.int32),
                          np.full(5, -1, np.int32),
                          np.zeros(eng.builder.p_max, np.int32))


def test_mk_chunked_lane_rejects_tiers_and_park():
    """The arena-tier limitation rejects stay proper
    NotImplementedErrors naming the limitation and the ROADMAP item
    tracking it, with chunked admission active."""
    srv = ServingEngine(_mk_engine(prefill_buckets=BUCKETS),
                        prefill_buckets=BUCKETS)
    h = srv.submit([1, 2, 3], max_new_tokens=8)
    srv.step()
    with pytest.raises(NotImplementedError, match="arena-tier"):
        srv.park(h)
    with pytest.raises(NotImplementedError, match="Open item 3"):
        srv.park(h)
