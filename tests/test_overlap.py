"""Shared overlap-engine tests (``triton_dist_tpu/lang/overlap.py``).

Three layers, matching the ISSUE-2 acceptance criteria:

1. Pure schedule arithmetic: permutation/inverse/slot properties of the
   rank-swizzled chunk orders, and ``choose_depth`` resolution.
2. Numerical parity on the CPU interpret mesh: for EVERY
   ``(swizzle_mode, prefetch_depth)`` in each op's config space, the
   fused kernel must match its oracle — the swizzle only reorders
   waits/compute, never the result.
3. The ``autotune`` decorator end-to-end on ag_gemm: sweep → winner
   persisted in the tune cache → cache hit (no re-sweep) on the second
   call; plus the in-trace ``ag_gemm_tuned`` cache-hit path.

Shapes follow test_fused_gemm.py's note: interpret mode on the CPU mesh
needs every buffer small, so these stay tiny — kernel logic is
shape-agnostic.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu import tune
from triton_dist_tpu.lang import overlap
from triton_dist_tpu.parallel.mesh import MeshContext
from triton_dist_tpu.utils.testing import spmd, assert_allclose

DEPTHS = (0, 1, 2, 3)   # 0 = auto; 1..3 = stage-and-wait/double/triple


def _rand(shape, seed, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype)


def _mesh1():
    return Mesh(np.array(jax.devices()[:1]), ("tp",))


# ---------------------------------------------------------------------------
# 1. schedule arithmetic
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("world", [1, 2, 4, 8])
@pytest.mark.parametrize("mode", overlap.SWIZZLE_MODES)
def test_schedule_is_permutation(world, mode):
    for rank in range(world):
        order = overlap.schedule(rank, world, world, mode)
        assert sorted(order) == list(range(world))


@pytest.mark.parametrize("world", [2, 4, 8])
def test_schedule_rank_anchors(world):
    """"ag"/"a2a" start on the locally-resident chunk (zero exposed
    latency); "rs" FINISHES each chunk at its owner (the running sum's
    last hop lands home)."""
    for rank in range(world):
        assert overlap.schedule(rank, world, world, "ag")[0] == rank
        assert overlap.schedule(rank, world, world, "a2a")[0] == rank
        assert overlap.schedule(rank, world, world, "rs")[-1] == rank
        assert overlap.schedule(rank, world, world, "identity") == \
            tuple(range(world))


@pytest.mark.parametrize("world", [1, 2, 4, 8])
@pytest.mark.parametrize("mode", overlap.SWIZZLE_MODES)
def test_chunk_at_step_of_inverse(world, mode):
    for rank in range(world):
        for s in range(world):
            c = overlap.chunk_at(s, rank, world, mode)
            assert overlap.step_of(c, rank, world, mode) == s


def test_schedule_arg_validation():
    with pytest.raises(ValueError, match="unknown swizzle_mode"):
        overlap.schedule(0, 4, 4, "zigzag")
    with pytest.raises(ValueError, match="schedules exactly world"):
        overlap.schedule(0, 4, 3, "ag")
    # identity allows any chunk count (plain grid order).
    assert overlap.schedule(0, 4, 6, "identity") == (0, 1, 2, 3, 4, 5)


def test_chunk_at_matches_traced_arithmetic():
    """Host ints and traced values must compute the same schedule (the
    kernels use traced grid indices, the hosts use ints)."""
    world = 8
    for mode in overlap.SWIZZLE_MODES:
        for rank in range(world):
            host = [overlap.chunk_at(s, rank, world, mode)
                    for s in range(world)]
            traced = jax.jit(lambda s, r, m=mode: overlap.chunk_at(
                s, r, world, m))
            got = [int(traced(jnp.int32(s), jnp.int32(rank)))
                   for s in range(world)]
            assert got == host, (mode, rank)


@pytest.mark.parametrize("world", [2, 4, 8])
def test_a2a_slot_bijection(world):
    """Per-destination, the n-1 sources map onto distinct slots
    0..n-2 — a consumer never blocks on traffic it does not read."""
    for dst in range(world):
        slots = [overlap.a2a_slot(src, dst, world)
                 for src in range(world) if src != dst]
        assert sorted(slots) == list(range(world - 1))
        # Receiver-side arithmetic in sp_ag_attention: chunk src is
        # processed at step k = (dst - src) mod n, waiting slot n-k-1.
        for src in range(world):
            if src == dst:
                continue
            k = (dst - src) % world
            assert overlap.a2a_slot(src, dst, world) == world - k - 1


def test_ring_chunk():
    assert overlap.ring_chunk(0, 3, 8) == 3         # local chunk
    assert overlap.ring_chunk(1, 3, 8) == 2         # left neighbour's
    assert overlap.ring_chunk(7, 0, 8) == 1


def test_choose_depth():
    kb = 1024
    # auto (0) = double buffering when it fits and there is a body to
    # hide staging under.
    assert overlap.choose_depth(0, 64 * kb, 1024 * kb, 4, 8) == 2
    # explicit depths honored when they fit...
    assert overlap.choose_depth(1, 64 * kb, 1024 * kb, 4, 8) == 1
    assert overlap.choose_depth(3, 64 * kb, 1024 * kb, 4, 8) == 3
    # ...clamped (never rejected) against the VMEM budget...
    assert overlap.choose_depth(3, 400 * kb, 1024 * kb, 4, 8) == 2
    assert overlap.choose_depth(3, 900 * kb, 1024 * kb, 4, 8) == 1
    # ...against the panel count...
    assert overlap.choose_depth(3, 64 * kb, 1024 * kb, 4, 2) == 2
    # ...and down to 1 when the chunk has no body ahead of the boundary.
    assert overlap.choose_depth(2, 64 * kb, 1024 * kb, 1, 8) == 1
    # chunk_len=None: staging is not cross-chunk, so the >=2-bodies
    # guard does not apply even at one body per chunk.
    assert overlap.choose_depth(2, 64 * kb, 1024 * kb, None, 8) == 2
    with pytest.raises(ValueError, match="prefetch_depth"):
        overlap.choose_depth(4, kb, kb, 4, 4)
    with pytest.raises(ValueError, match="prefetch_depth"):
        overlap.choose_depth(-1, kb, kb, 4, 4)


# ---------------------------------------------------------------------------
# 2. swizzled-vs-identity numerical parity, full config space
# ---------------------------------------------------------------------------

def _ag_modes():
    from triton_dist_tpu.ops.ag_gemm import SWIZZLE_MODES
    return SWIZZLE_MODES


@pytest.mark.parametrize("variant,mode,depth",
                         list(itertools.product(("panel", "pipelined"),
                                                ("ag", "identity"),
                                                DEPTHS)))
def test_ag_gemm_parity(tp8_mesh, tp8_ctx, variant, mode, depth):
    from triton_dist_tpu.ops import ag_gemm, create_ag_gemm_context

    assert mode in _ag_modes()
    a = _rand((128, 32), 70)
    b = _rand((32, 64), 71)
    # m_loc=16/block_m=8 -> 2 bodies per chunk, so cross-chunk
    # prefetch (depth >= 2) genuinely engages for the panel variant;
    # block_k=16 -> n_k=2, so the pipelined variant's scoped stream
    # genuinely double-buffers.
    ctx = create_ag_gemm_context(tp8_ctx, block_m=8, block_n=8,
                                 block_k=16, variant=variant,
                                 swizzle_mode=mode, prefetch_depth=depth)
    f = spmd(tp8_mesh, lambda x, w: ag_gemm(x, w, ctx),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    # Host oracle: the column-sharded outputs reassemble to the full
    # product (no second interpret compile per test).
    assert_allclose(f(a, b), jnp.dot(a, b), rtol=1e-4, atol=1e-4)


def test_ag_gemm_swizzled_equals_identity(tp8_mesh, tp8_ctx):
    """Direct parity of the two schedules (not just oracle-closeness):
    the swizzle reorders chunk traversal, and chunk contributions land
    in disjoint output rows, so outputs are bit-identical."""
    from triton_dist_tpu.ops import ag_gemm, create_ag_gemm_context

    a = _rand((128, 32), 72)
    b = _rand((32, 64), 73)
    outs = {}
    for mode in ("ag", "identity"):
        ctx = create_ag_gemm_context(tp8_ctx, block_m=8, block_n=8,
                                     swizzle_mode=mode)
        outs[mode] = np.asarray(
            spmd(tp8_mesh, lambda x, w: ag_gemm(x, w, ctx),
                 (P("tp", None), P(None, "tp")), P(None, "tp"))(a, b))
    np.testing.assert_array_equal(outs["ag"], outs["identity"])


@pytest.mark.parametrize("mode,depth",
                         list(itertools.product(("ag", "identity"),
                                                (0, 3))))
def test_ag_gemm_variant_bit_parity(tp8_mesh, tp8_ctx, mode, depth):
    """Panel and pipelined must be BIT-identical, not just close: at
    equal tile sizes both accumulate the same (tm, tk) x (tk, tn)
    partial products in the same ascending-K order into an f32
    accumulator — different staging, same arithmetic."""
    from triton_dist_tpu.ops import ag_gemm, create_ag_gemm_context

    a = _rand((128, 32), 78)
    b = _rand((32, 64), 79)
    outs = {}
    for variant in ("panel", "pipelined"):
        ctx = create_ag_gemm_context(tp8_ctx, block_m=8, block_n=8,
                                     block_k=16, variant=variant,
                                     swizzle_mode=mode,
                                     prefetch_depth=depth)
        outs[variant] = np.asarray(
            spmd(tp8_mesh, lambda x, w: ag_gemm(x, w, ctx),
                 (P("tp", None), P(None, "tp")), P(None, "tp"))(a, b))
    np.testing.assert_array_equal(outs["panel"], outs["pipelined"])


@pytest.mark.parametrize("ring", (2, 4, 8))
def test_ag_gemm_sim_ring_sweep(ring):
    """Both variants across self-ring sizes (the bench's single-chip
    overlap proxy at each world size): oracle parity per variant and
    bit-parity between variants on every ring."""
    from triton_dist_tpu.ops import ag_gemm, create_ag_gemm_context

    mesh1 = _mesh1()
    ctx1 = MeshContext.from_mesh(mesh1)
    a = _rand((128, 32), 80)
    b = _rand((32, 64), 81)
    outs = {}
    for variant in ("panel", "pipelined"):
        ctx = create_ag_gemm_context(ctx1, block_m=8, block_n=8,
                                     block_k=16, variant=variant)
        outs[variant] = np.asarray(
            spmd(mesh1,
                 lambda x, w: ag_gemm(x, w, ctx, sim_ranks=ring),
                 (P(None, None), P(None, None)), P(None, None))(a, b))
        assert_allclose(outs[variant], jnp.dot(a, b),
                        rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(outs["panel"], outs["pipelined"])


@pytest.mark.parametrize("mode,depth",
                         list(itertools.product(("rs", "identity"),
                                                DEPTHS)))
def test_gemm_rs_parity(tp8_mesh, tp8_ctx, mode, depth):
    from triton_dist_tpu.ops import create_gemm_rs_context, gemm_rs

    a = _rand((128, 128), 74)
    b = _rand((128, 64), 75)
    ctx = create_gemm_rs_context(tp8_ctx, block_m=16, block_n=32,
                                 swizzle_mode=mode, prefetch_depth=depth)
    f = spmd(tp8_mesh, lambda x, w: gemm_rs(x, w, ctx),
             (P(None, "tp"), P("tp", None)), P("tp", None))
    # Host oracle: the row-scattered shards reassemble to the full
    # product.
    assert_allclose(f(a, b), jnp.dot(a, b), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("mode,depth",
                         list(itertools.product(("a2a", "identity"),
                                                DEPTHS)))
def test_a2a_gemm_parity(tp8_mesh, tp8_ctx, mode, depth):
    from triton_dist_tpu.ops.a2a_gemm import (a2a_gemm_fused,
                                              create_a2a_gemm_context)

    x = _rand((64, 2, 32), 76)   # per-shard (8, 2, 32)
    w = _rand((32, 16), 77)
    fctx = create_a2a_gemm_context(tp8_ctx, "tp", swizzle_mode=mode,
                                   prefetch_depth=depth)
    f = spmd(tp8_mesh, lambda v, ww: a2a_gemm_fused(v, ww, fctx),
             (P("tp", None, None), P(None, None)), P("tp", None))
    # Host oracle: rank r's recv[src] = shard src's chunk r, so its
    # output rows are concat_src(x[8*src + r]) @ w.
    xs, wn = np.asarray(x), np.asarray(w)
    want = np.concatenate([
        np.concatenate([xs[8 * src + r] for src in range(8)]) @ wn
        for r in range(8)])
    assert_allclose(f(x, w), want, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("mode,depth",
                         list(itertools.product(("a2a", "identity"),
                                                DEPTHS)))
def test_ulysses_o_a2a_gemm_parity(mode, depth):
    """Consumer-side Ulysses kernel under the full config space, on the
    single-chip sim ring (the multi-rank form routes to XLA on the
    interpret mesh — the sim runs the REAL swizzled kernel schedule)."""
    from triton_dist_tpu.ops.ulysses_fused import (
        create_ulysses_fused_context, o_a2a_gemm)

    mesh1 = _mesh1()
    ctx1 = MeshContext.from_mesh(mesh1)
    n, s_loc, rows, d = 4, 8, 8, 16
    o = _rand((n * s_loc, rows), 78) * 0.3
    w = _rand((n, rows, d), 79) * 0.3
    ctx = create_ulysses_fused_context(ctx1, axis="tp", block_m=8,
                                       block_n=8, swizzle_mode=mode,
                                       prefetch_depth=depth)
    f = spmd(mesh1, lambda x, ww: o_a2a_gemm(x, ww, ctx, sim_ranks=n),
             (P(None, None), P(None, None, None)), P(None, None))
    want = jnp.einsum("nsr,nrd->sd", o.reshape(n, s_loc, rows), w)
    assert_allclose(f(o, w), want, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("depth", DEPTHS)
def test_sp_ag_attention_prefetch_depth_parity(depth):
    """KV-stager depth knob on the self-sim ring: every depth must
    reproduce dense causal attention of the last rank's query slice."""
    from triton_dist_tpu.ops import sp_ag_attention_fused
    from triton_dist_tpu.ops.sp_ag_attention import _masked_attn

    mesh1 = _mesh1()
    ctx1 = MeshContext.from_mesh(mesh1)
    s, h, kvh, hd, n_sim = 32, 4, 2, 16, 4
    q = _rand((s, h, hd), 80) * 0.5
    k = _rand((s, kvh, hd), 81) * 0.5
    v = _rand((s, kvh, hd), 82) * 0.5
    out = spmd(mesh1,
               lambda a, b, c: sp_ag_attention_fused(
                   a, b, c, ctx=ctx1, axis="tp", block_q=4, block_kv=8,
                   sim_ranks=n_sim, prefetch_depth=depth),
               (P(None, None, None),) * 3, P(None, None, None))(q, k, v)
    s_loc = s // n_sim
    want = _masked_attn(q[-s_loc:], k, v, (n_sim - 1) * s_loc)
    assert_allclose(out, want, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# 3. autotune end-to-end on ag_gemm
# ---------------------------------------------------------------------------

@pytest.fixture
def fresh_tune_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("TRITON_DIST_TPU_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(tune, "_CACHE_PATH", None)
    monkeypatch.setattr(tune, "_CACHE", None)
    yield tmp_path
    tune._CACHE_PATH = None
    tune._CACHE = None


def test_autotune_ag_gemm_end_to_end(tp8_mesh, tp8_ctx, fresh_tune_cache,
                                     monkeypatch):
    """The decorator's full loop on ag_gemm: sweep every config (each
    one actually dispatched through the fused kernel), persist the
    winner, and hit the cache — no timing — on the second call."""
    import triton_dist_tpu.autotuner as autotuner
    from triton_dist_tpu.ops import ag_gemm, create_ag_gemm_context

    # Single-dispatch timer: the chained-slope harness is a hardware
    # measurement tool — one interpret-mode run per config is enough to
    # drive the sweep deterministically here.
    timed = []

    def quick_perf(fn, args, **_):
        import time
        t0 = time.perf_counter()
        np.asarray(fn(*args))
        timed.append(1)
        return time.perf_counter() - t0

    monkeypatch.setattr(autotuner, "perf_func", quick_perf)

    configs = [
        {"block_m": 8, "block_n": 8},
        {"block_m": 8, "block_n": 8, "swizzle_mode": "identity"},
        {"block_m": 8, "block_n": 8, "prefetch_depth": 1},
    ]

    @autotuner.autotune(
        "ag_gemm_e2e_test", configs,
        key_fn=lambda a_, b_: {
            "m": a_.shape[0], "k": a_.shape[1], "n": b_.shape[1],
            "dtype": str(a_.dtype), "mesh": tune.mesh_key(tp8_ctx)})
    def run(a_, b_, block_m=8, block_n=8, swizzle_mode="ag",
            prefetch_depth=0):
        ctx = create_ag_gemm_context(
            tp8_ctx, "tp", block_m, block_n, 512,
            swizzle_mode=swizzle_mode, prefetch_depth=prefetch_depth)
        return spmd(tp8_mesh, lambda x, w: ag_gemm(x, w, ctx),
                    (P("tp", None), P(None, "tp")), P(None, "tp"))(a_, b_)

    a = _rand((128, 32), 83)
    b = _rand((32, 64), 84)
    want = jnp.dot(a, b)

    # First call: sweeps all three configs, persists the winner.
    assert_allclose(run(a, b), want, rtol=1e-4, atol=1e-4)
    assert len(timed) == len(configs)
    key = tune.make_key("ag_gemm_e2e_test", m=128, k=32, n=64,
                        dtype=str(a.dtype), mesh=tune.mesh_key(tp8_ctx))
    winner = tune.load_autotune_data(key)
    assert winner in configs

    # Second call: cache hit — any timing attempt is a test failure.
    def no_timing(*_a, **_k):   # pragma: no cover
        raise AssertionError("cache hit must not re-sweep")

    monkeypatch.setattr(autotuner, "perf_func", no_timing)
    assert_allclose(run(a, b), want, rtol=1e-4, atol=1e-4)


def test_ag_gemm_tuned_in_trace_uses_cached_winner(tp8_mesh, tp8_ctx,
                                                   fresh_tune_cache):
    """ag_gemm_tuned inside shard_map (tracers: nothing can be timed)
    must pick up a pre-persisted winner keyed on (mesh shape, shard
    M/K/N, dtype) — the persistent-cache contract for the fused-op
    family."""
    from triton_dist_tpu.ops import ag_gemm_tuned

    a = _rand((128, 32), 85)
    b = _rand((32, 64), 86)
    m_loc, k = 128 // 8, 32
    n_loc = 64 // 8
    key = tune.make_key("ag_gemm", m=m_loc, k=k, n=n_loc,
                        dtype=str(a.dtype), world=8,
                        mesh=tune.mesh_key(tp8_ctx))
    winner = {"block_m": 8, "block_n": 8, "block_k": 32,
              "swizzle_mode": "identity", "prefetch_depth": 1}
    tune.store_autotune_data(key, winner)

    f = spmd(tp8_mesh,
             lambda x, w: ag_gemm_tuned(x, w, tp8_ctx, axis="tp"),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    assert_allclose(f(a, b), jnp.dot(a, b), rtol=1e-4, atol=1e-4)


def test_tune_ag_gemm_variant_round_trip(fresh_tune_cache, monkeypatch):
    """The offline variant sweep's persistent-cache contract: the first
    call times BOTH variants on the sim ring and persists the winner
    (plus per-variant partials); the second call returns the cached
    winner without dispatching a single kernel; resolve_ag_variant
    ("auto") reads the same record."""
    import importlib

    mod = importlib.import_module("triton_dist_tpu.ops.ag_gemm")
    mesh1 = _mesh1()
    shape = dict(axis="tp", m=32, k=32, n=64, dtype=jnp.float32,
                 block_m=8, block_n=8, block_k=16)

    dispatched = []
    real_impl = mod._ag_gemm_impl

    def spy(*a_, **k_):
        dispatched.append(k_.get("ctx", a_[2] if len(a_) > 2 else None))
        return real_impl(*a_, **k_)

    monkeypatch.setattr(mod, "_ag_gemm_impl", spy)

    winner = mod.tune_ag_gemm_variant(mesh1, sim_ranks=4, reps=1, **shape)
    assert winner in ("panel", "pipelined")
    assert dispatched, "sweep must actually dispatch kernels"

    mctx = MeshContext.from_mesh(mesh1)
    rec = tune.load_autotune_data(mod._variant_key(mctx, **shape))
    assert rec["variant"] == winner
    # Both variants measured: the sweep is a comparison, not a default.
    assert set(rec["times_ms"]) == {"panel", "pipelined"}
    for variant in ("panel", "pipelined"):
        partial = tune.load_autotune_data(tune.make_key(
            "ag_gemm_variant_partial",
            base=mod._variant_key(mctx, **shape), cfg=variant))
        assert partial == {"variant": variant,
                           "ms": rec["times_ms"][variant]}

    # Cache hit: any dispatch on the second call is a test failure.
    dispatched.clear()
    assert mod.tune_ag_gemm_variant(mesh1, sim_ranks=4, reps=1,
                                    **shape) == winner
    assert not dispatched
    assert mod.resolve_ag_variant("auto", mctx, **shape) == winner
    # Explicit variants bypass the cache entirely.
    assert mod.resolve_ag_variant("pipelined", mctx, **shape) == "pipelined"
