"""Speculative multi-token decoding battery.

The contract under test: speculation changes THROUGHPUT, never tokens
— greedy outputs through the K-token verification dispatch are
bit-identical to the non-speculative serving run (which is itself
token-exact vs ``Engine.serve``), across draft quality, rollback,
preemption mid-draft, and fault injection; and the verification
dispatch never re-specializes (K is static, acceptance is data).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu.models import Engine, ModelConfig
from triton_dist_tpu.resilience import faults
from triton_dist_tpu.serving import (
    NgramDraft, OutOfPagesError, Request, ServingEngine, accept_greedy,
)

TP = 4
CFG = ModelConfig.tiny()
MAX_LEN = 64
PAGE = 8


@pytest.fixture(scope="module")
def engine():
    mesh = Mesh(np.array(jax.devices()[:TP]), ("tp",))
    return Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=3)


def _baseline(engine, prompt, gen_len):
    ids = jnp.asarray(np.tile(np.asarray([prompt], np.int32), (TP, 1)))
    return np.asarray(engine.serve(ids, gen_len=gen_len))[0].tolist()


# ---------------------------------------------------------------------------
# draft proposer + acceptance rule (pure host logic)
# ---------------------------------------------------------------------------

def test_ngram_draft_proposes_from_history():
    d = NgramDraft(n=2)
    # trailing (2, 3) last occurred earlier, followed by 9, 2:
    assert d.propose([1, 2, 3, 9, 2, 3], 2) == [9, 2]
    # no earlier match anywhere: repeat the last token
    assert d.propose([5, 6, 7], 3) == [7, 7, 7]
    # short continuation CYCLES the matched suffix
    assert d.propose([4, 8, 4, 8], 3) == [4, 8, 4]
    # deterministic: same history, same proposal
    h = list(np.random.RandomState(0).randint(0, 9, 30))
    assert d.propose(h, 4) == d.propose(list(h), 4)


def test_accept_greedy_rule():
    # t_1 always commits; t_j commits iff t_{j-1} == d_j.
    assert accept_greedy([5, 7, 8, 9], [7, 8, 9, 1]) == 4   # exact draft
    assert accept_greedy([5, 7, 8, 9], [7, 8, 2, 1]) == 3   # d_4 != t_3
    assert accept_greedy([5, 0, 0, 0], [7, 8, 9, 1]) == 1   # miss at once
    assert accept_greedy([5], [7]) == 1                     # K=1 degenerate


# ---------------------------------------------------------------------------
# token-exactness: acceptance + rollback determinism vs the non-spec run
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("spec_k", [1, 2, 4])
def test_spec_token_exact_vs_nonspec(engine, spec_k):
    """Greedy outputs are bit-identical with speculation on, for the
    K=1 degenerate case (exact self-draft) through K=4 (mixed
    accept/reject rollback every dispatch)."""
    prompts = [[1, 2, 3, 1, 2, 3], [4, 5], [6, 7, 8, 9], [5, 5, 5]]
    want = [_baseline(engine, p, 10) for p in prompts]
    srv = ServingEngine(engine, num_slots=2, page=PAGE, spec_k=spec_k)
    got = srv.generate(prompts, max_new_tokens=10)
    assert got == want
    st = srv.stats()
    assert st["spec"]["k"] == spec_k
    if spec_k > 1:
        # The repetitive prompts must have amortized some dispatches.
        assert st["spec"]["tokens_per_dispatch"] > 1.0


def test_spec_fewer_dispatches_on_repetitive_trace(engine):
    """The point of the feature: accepted tokens amortize dispatches
    (the CPU bench's serving_tokens_per_s_spec ratio rides this)."""
    prompts = [[1, 2, 3, 1, 2, 3, 1, 2], [7, 8, 7, 8, 7, 8]]
    base = ServingEngine(engine, num_slots=1, page=PAGE)
    spec = ServingEngine(engine, num_slots=1, page=PAGE, spec_k=4)
    want = base.generate(prompts, max_new_tokens=24)
    got = spec.generate(prompts, max_new_tokens=24)
    assert got == want
    d_base = base.stats()["decode_dispatches"]
    d_spec = spec.stats()["decode_dispatches"]
    assert d_spec < d_base, (d_spec, d_base)
    assert spec.stats()["spec"]["accepted"] > 0


def test_spec_eos_and_budget_mid_block(engine):
    """EOS landing mid-verification-block and a max_new_tokens budget
    smaller than K both truncate emission exactly like the sequential
    run (the over-budget candidates' writes land in scratch)."""
    want = _baseline(engine, [1, 2, 3], 3)
    srv = ServingEngine(engine, num_slots=2, page=PAGE, spec_k=4)
    h = srv.submit([1, 2, 3], max_new_tokens=3)   # budget < K
    srv.run()
    assert h.tokens == want
    # EOS: pick the baseline's second token as eos — the spec run must
    # stop at it even when the block carried more accepted tokens.
    eos = want[1]
    want_eos = want[:want.index(eos) + 1]
    srv2 = ServingEngine(engine, num_slots=2, page=PAGE, spec_k=4)
    h2 = srv2.submit([1, 2, 3], max_new_tokens=10, eos_id=eos)
    srv2.run()
    assert h2.tokens == want_eos


def test_spec_sampled_requests_commit_one_exact_token(engine):
    """Non-greedy requests ride the same dispatch but commit exactly
    one token per dispatch from position 0's exact logits — identical
    to their non-spec sampled run (same seed fold)."""
    req = dict(max_new_tokens=6, temperature=0.8, top_k=4, seed=11)
    base = ServingEngine(engine, num_slots=2, page=PAGE)
    hb = base.submit([3, 1, 4], **req)
    base.run()
    spec = ServingEngine(engine, num_slots=2, page=PAGE, spec_k=4)
    hs = spec.submit([3, 1, 4], **req)
    spec.run()
    assert hs.tokens == hb.tokens


# ---------------------------------------------------------------------------
# fixed shape / no recompile
# ---------------------------------------------------------------------------

def test_spec_fixed_shape_no_recompile(engine):
    """The verification dispatch compiles ONCE: requests joining and
    leaving, full/partial acceptance, and budget-clamped tail blocks
    are all data."""
    srv = ServingEngine(engine, num_slots=2, page=PAGE, spec_k=3)
    srv.generate([[1, 2]], max_new_tokens=2)        # warmup
    assert srv.decode_cache_size() == 1
    prompts = [[1, 2, 3, 1, 2, 3], [4, 5], [6, 7, 8], [9], [2, 4, 6]]
    srv.generate(prompts, max_new_tokens=9)
    assert srv.decode_cache_size() == 1, "verify dispatch re-specialized"


# ---------------------------------------------------------------------------
# preemption + rollback machinery
# ---------------------------------------------------------------------------

def test_spec_preemption_mid_draft_token_exact(engine):
    """Pool exhaustion while pre-allocating a draft block's pages
    preempts that request (pages freed, requeued, resumed via the
    deterministic re-prefill) — outputs still bit-exact."""
    prompts = [[1, 2, 3, 4, 5, 6], [7, 8, 9, 10, 11, 12]]
    want = [_baseline(engine, p, 4) for p in prompts]
    srv = ServingEngine(engine, num_slots=2, page=PAGE, num_pages=3,
                        spec_k=4)
    hs = [srv.submit(p, max_new_tokens=4) for p in prompts]
    srv.run()
    assert [h.status for h in hs] == ["done", "done"]
    assert [h.tokens for h in hs] == want
    assert srv.stats()["preemptions"] >= 1


def test_spec_truncate_never_frees_prefix_shared_pages(engine):
    """Rollback's page-level truncate keeps the slot's prefix-hit run:
    two same-prefix requests sharing pages decode speculatively
    without ever freeing (or corrupting) the shared pages."""
    shared = list(range(1, PAGE + 1))       # exactly one full page
    p1 = shared + [20, 21]
    p2 = shared + [30]
    want = [_baseline(engine, p, 6) for p in (p1, p2)]
    srv = ServingEngine(engine, num_slots=2, page=PAGE, spec_k=4,
                        prefix_reuse=True)
    got = srv.generate([p1, p2], max_new_tokens=6)
    assert got == want
    assert srv.stats()["pool"]["prefix_hits"] >= 1


# ---------------------------------------------------------------------------
# fault containment
# ---------------------------------------------------------------------------

def test_spec_dropped_verification_fails_one_request(engine):
    """A fault plan dropping a verification dispatch fails the
    scheduler's victim, not the server; the survivor's output stays
    token-exact."""
    srv = ServingEngine(engine, num_slots=2, page=PAGE, spec_k=3)
    doomed = srv.submit([1, 2], max_new_tokens=6)
    srv.step()                                # doomed decodes first
    ok = srv.submit([6, 7, 8], max_new_tokens=5)
    with faults.inject(faults.get_plan("fail_kth_call",
                                       op="spec_verify", k=0)):
        srv.run()
    assert doomed.status == "failed"
    assert isinstance(doomed.error, faults.InjectedFault)
    assert ok.status == "done"
    assert ok.tokens == _baseline(engine, [6, 7, 8], 5)
    assert srv.stats()["pool"]["used_pages"] == 0, "pages leaked"


# ---------------------------------------------------------------------------
# knob validation
# ---------------------------------------------------------------------------

# One megakernel engine per build config for the whole module —
# engine builds dominate wall clock, and reuse is the serving layer's
# slot-recycling contract (positions rewrite, lengths mask).
_MK_CACHE: dict = {}


def _mk_engine(**kw):
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    key = tuple(sorted(kw.items()))
    if key not in _MK_CACHE:
        mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
        base = dict(batch=2, max_len=64, tile_w=16, t_tile=16,
                    paged=True, page=16, num_pages=9)
        base.update(kw)
        _MK_CACHE[key] = MegaKernelEngine(
            ModelConfig.tiny(vocab_size=128), mesh, **base)
    return _MK_CACHE[key]


def test_megakernel_spec_token_exact_vs_nonspec():
    """The converted mk-reject: spec_k=2 on the megakernel under
    schedule='dynamic' (the scoreboard claims the verification chains)
    is token-exact vs the non-spec mk run on the repetitive trace —
    the Q-block verification rows' logits are bit-identical to the
    sequential decode body's, so greedy acceptance commits exactly
    the sequential tokens — with > 1 tokens per dispatch measured."""
    rep = [[1, 2, 3, 1, 2, 3, 1, 2], [7, 8, 7, 8, 7, 8]]
    want = ServingEngine(_mk_engine()).generate(rep, max_new_tokens=16)
    srv = ServingEngine(_mk_engine(spec_k=2, schedule="dynamic"),
                        spec_k=2)
    got = srv.generate(rep, max_new_tokens=16)
    assert got == want
    st = srv.stats()
    assert st["spec"]["k"] == 2
    assert st["spec"]["tokens_per_dispatch"] > 1.0, st["spec"]
    assert st["mk_spec"] == 2
    # The verification dispatch never re-specializes: requests
    # joining/leaving, acceptance patterns, and budget-clamped tails
    # are all data.
    n = srv.decode_cache_size()
    srv.generate([[4, 4, 4]], max_new_tokens=4)
    assert srv.decode_cache_size() == n, "mk verify re-specialized"


def test_megakernel_spec_eos_budget_and_sampled():
    """EOS mid-block, a max_new budget smaller than K (over-budget
    rows MASKED in-kernel, never touching real pages), and sampled
    requests (one exact token per dispatch) all match the non-spec
    megakernel run."""
    want = ServingEngine(_mk_engine()).generate([[1, 2, 3]],
                                                max_new_tokens=3)[0]
    srv = ServingEngine(_mk_engine(spec_k=4), spec_k=4)
    h = srv.submit([1, 2, 3], max_new_tokens=3)     # budget < K
    srv.run()
    assert h.tokens == want
    eos = want[1]
    srv2 = ServingEngine(_mk_engine(spec_k=4), spec_k=4)
    h2 = srv2.submit([1, 2, 3], max_new_tokens=10, eos_id=eos)
    srv2.run()
    assert h2.tokens == want[:want.index(eos) + 1]
    req = dict(max_new_tokens=5, temperature=0.8, top_k=4, seed=11)
    base = ServingEngine(_mk_engine())
    hb = base.submit([3, 1, 4], **req)
    base.run()
    spec = ServingEngine(_mk_engine(spec_k=4), spec_k=4)
    hs = spec.submit([3, 1, 4], **req)
    spec.run()
    assert hs.tokens == hb.tokens


def test_megakernel_spec_knob_validation():
    """spec_k is an ENGINE knob on the mk lane: serving/engine
    mismatch, non-paged builds, and hybrid builds fail loudly."""
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    with pytest.raises(ValueError, match="spec_k mismatch"):
        ServingEngine(_mk_engine(), spec_k=2)
    with pytest.raises(ValueError, match="paged"):
        MegaKernelEngine(ModelConfig.tiny(vocab_size=128), mesh,
                         batch=2, max_len=32, tile_w=16, t_tile=16,
                         spec_k=2)
    hcfg = ModelConfig.tiny_next(vocab_size=128, num_key_value_heads=4,
                                 full_attn_interval=2)
    with pytest.raises(NotImplementedError, match="hybrid"):
        MegaKernelEngine(hcfg, mesh, batch=2, max_len=32, tile_w=16,
                         t_tile=16, paged=True, page=16, spec_k=2)
