"""Quantized paged KV serving battery (kv_dtype="int8"|"fp8").

Gates, in order of importance:

1. the NON-quantized path stays bit-identical to ``Engine.serve``
   (the pre-existing token-exactness contract must not regress just
   because the quantized machinery exists);
2. the quantized path's divergence is BOUNDED — a direct logit
   max-abs-err gate on one decode dispatch against the bf16 pool, and
   a greedy-token agreement gate over whole served requests (surfaced
   via ``stats()["greedy_agreement"]``);
3. the capacity win is real and reported: int8 ≥ 1.9x pages at fixed
   pool bytes per ``BlockManager`` stats;
4. quantization composes with the rest of the serving stack (chunked
   prefill, prefix reuse, disaggregated migration, speculation).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import triton_dist_tpu as tdt
from triton_dist_tpu.models import Engine, ModelConfig, dense
from triton_dist_tpu.serving import PagedKVCache, ServingEngine

TP = 4
CFG = ModelConfig.tiny()
MAX_LEN = 64
PAGE = 8


@pytest.fixture(scope="module")
def engine():
    mesh = Mesh(np.array(jax.devices()[:TP]), ("tp",))
    return Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=3)


def _baseline(engine, prompt, gen_len):
    ids = jnp.asarray(np.tile(np.asarray([prompt], np.int32), (TP, 1)))
    return np.asarray(engine.serve(ids, gen_len=gen_len))[0].tolist()


PROMPTS = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 10], [3, 1, 4, 1, 5]]


def test_unquantized_path_still_token_exact(engine):
    """kv_dtype='bf16' (and the default) run the ORIGINAL pool code —
    outputs bit-identical to Engine.serve, scales absent."""
    want = [_baseline(engine, p, 8) for p in PROMPTS]
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        kv_dtype="bf16")
    assert srv.cache.k_scale is None
    got = srv.generate(PROMPTS, max_new_tokens=8)
    assert got == want


def test_quantized_logit_divergence_bounded(engine):
    """One decode dispatch over identically-prefilled bf16 vs int8/fp8
    pools: logit max-abs-err under a fixed threshold (the CPU
    battery's bounded-divergence gate for the fused-dequant path) —
    the SAME token fed over the same prompt, only the pool storage
    differs."""
    prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11]

    def first_decode_logits(kvd):
        srv = ServingEngine(engine, num_slots=2, page=PAGE,
                            kv_dtype=kvd)
        h = srv.submit(prompt, max_new_tokens=2)
        stalled = []
        for hh in srv.sched.admit():
            srv._admit(hh, stalled)     # prefill + blit; exact token 1
        srv._toks[0] = h.tokens[-1]
        srv.manager.append(0, int(srv._lens[0]))
        tbl = np.zeros((srv.num_slots, srv.p_max), np.int32)
        tbl[0] = srv.manager.table_row(0)
        return srv._dispatch(tbl)[0]

    base = first_decode_logits("bf16")
    # Thresholds: the CPU battery's empirical bound with ~5x margin
    # (measured: int8 ~3e-3, fp8 ~1e-2 on this tiny config).
    for kvd, thresh in (("int8", 0.05), ("fp8", 0.15)):
        err = np.abs(first_decode_logits(kvd) - base).max()
        assert err < thresh, f"{kvd} logit divergence {err}"


@pytest.mark.parametrize("kvd,min_agree", [("int8", 0.7), ("fp8", 0.5)])
def test_quantized_greedy_agreement_surfaced(engine, kvd, min_agree):
    """Whole-request greedy agreement vs the exact run, folded into
    stats() via compare_greedy — the serving-level accuracy surface."""
    want = [_baseline(engine, p, 8) for p in PROMPTS]
    srv = ServingEngine(engine, num_slots=2, page=PAGE, kv_dtype=kvd)
    got = srv.generate(PROMPTS, max_new_tokens=8)
    agree = srv.compare_greedy(zip(got, want))
    st = srv.stats()
    assert st["greedy_agreement"] == agree
    assert agree >= min_agree, (kvd, agree, got, want)
    assert st["kv_dtype"] == kvd


def test_int8_capacity_ratio_gate(engine):
    """int8 KV buys >= 1.9x pages at fixed pool bytes — reported by
    the BlockManager stats and the model plan."""
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        kv_dtype="int8")
    pool = srv.stats()["pool"]
    assert pool["capacity_ratio_vs_native"] >= 1.9, pool
    assert pool["bytes_per_token"] < srv.plan[
        "native_page_bytes_per_rank"] / PAGE
    assert srv.plan["capacity_ratio_vs_native"] >= 1.9
    # pages_at_native_bytes: what the SAME HBM would hold quantized.
    assert pool["pages_at_native_bytes"] >= int(
        1.9 * (pool["num_pages"] - 1))


def test_quantized_chunked_prefill_and_prefix_reuse(engine):
    """Quantization composes with the bucketed chunk stream and
    refcounted prefix sharing: shared pages keep the first sharer's
    bytes AND scales; chunk boundaries do not shift the numerics
    regime (greedy agreement holds)."""
    shared = list(range(1, PAGE + 1))
    prompts = [shared + [20, 21], shared + [30]]
    want = [_baseline(engine, p, 6) for p in prompts]
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        kv_dtype="int8", prefix_reuse=True,
                        prefill_buckets=(4,))
    # Sequential submits: prefix pages publish at commit (end of the
    # first chunk stream), so the second request must arrive after.
    got = [srv.generate([prompts[0]], max_new_tokens=6)[0],
           srv.generate([prompts[1]], max_new_tokens=6)[0]]
    assert srv.stats()["pool"]["prefix_hits"] >= 1
    agree = srv.compare_greedy(zip(got, want))
    assert agree >= 0.6, (agree, got, want)
    assert srv.prefill_cache_size() <= 1


def test_quantized_disagg_migration_bit_exact():
    """Pages migrate as their STORED bytes + scales: the decode-side
    pool holds bit-identical int8 content after the handoff (scatter
    without scales is rejected)."""
    import os

    from triton_dist_tpu.serving import DisaggServingEngine

    cfg = ModelConfig.tiny()
    devs = jax.devices()
    params = dense.init_params(jax.random.PRNGKey(0), cfg)
    pf = Engine(cfg, tdt.make_mesh(tp=1, devices=devs[:1]), mode="xla",
                max_len=MAX_LEN, params=params)
    dec = Engine(cfg, tdt.make_mesh(tp=1, devices=devs[1:2]),
                 mode="xla", max_len=MAX_LEN, params=params)
    srv = DisaggServingEngine(dec, prefill_engine=pf, num_slots=2,
                              page=PAGE, prefill_buckets=(4,),
                              kv_dtype="int8")
    h = srv.submit([1, 2, 3, 4, 5, 6, 7, 8, 9], max_new_tokens=2)
    # Drive chunks until the migration is issued, then capture the
    # staging pages BEFORE the scatter consumes them.
    for _ in range(20):
        if srv._pending:
            break
        srv.step()
    assert srv._pending, "migration never issued"
    _, _, payload, dst_ids, _, _, _ = srv._pending[0]
    k_pay = np.asarray(payload[0])
    ks_pay = np.asarray(payload[2])
    # Collect the migration and compare BEFORE any decode append can
    # requantize the slot's (partially-filled) final page.
    srv._complete_migrations()
    assert not srv._pending
    # Only the real destination rows carry the payload — scratch-
    # padded rows (dropped prefix/padding) are garbage by contract.
    sel = np.asarray(dst_ids) != 0
    got = np.asarray(srv.cache.k_pages[:, dst_ids])[:, sel]
    got_s = np.asarray(srv.cache.k_scale[:, dst_ids])[:, sel]
    np.testing.assert_array_equal(
        got.view(np.uint8), k_pay[:, sel].view(np.uint8))
    np.testing.assert_array_equal(got_s, ks_pay[:, sel])
    srv.run()
    assert h.status == "done"


def test_scatter_scale_mismatch_raises():
    c_q = PagedKVCache.empty(1, 4, PAGE, 2, 8, num_slots=1, p_max=2,
                             kv_dtype="int8")
    c_n = PagedKVCache.empty(1, 4, PAGE, 2, 8, num_slots=1, p_max=2)
    ids = jnp.asarray([1, 2], jnp.int32)
    pay = c_q.gather_pages(ids)
    with pytest.raises(ValueError, match="needs the payload's"):
        c_q.scatter_pages(pay[0], pay[1], ids)
    with pytest.raises(ValueError, match="unquantized"):
        c_n.scatter_pages(np.zeros((1, 2, 2, PAGE, 8), np.float32),
                          np.zeros((1, 2, 2, PAGE, 8), np.float32),
                          ids, pay[2], pay[3])


def test_quantized_spec_composes(engine):
    """Speculation over a quantized pool: self-consistent (spec on/off
    produce the SAME quantized-path tokens) — the rollback path's
    scratch routing keeps rejected candidates out of real pages."""
    srv_q = ServingEngine(engine, num_slots=2, page=PAGE,
                          kv_dtype="int8")
    want = srv_q.generate(PROMPTS, max_new_tokens=8)
    srv_sq = ServingEngine(engine, num_slots=2, page=PAGE,
                           kv_dtype="int8", spec_k=4)
    got = srv_sq.generate(PROMPTS, max_new_tokens=8)
    assert got == want


def _mk_cfg():
    return ModelConfig.tiny(vocab_size=128)


# One megakernel engine per kv_dtype for the whole module: engine
# builds dominate the battery's wall clock, and reuse is exactly the
# serving layer's slot-recycling contract (positions rewrite, lengths
# mask — stale pool bytes are never read).
_MK_CACHE: dict = {}


def _mk_engine(**kw):
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    key = tuple(sorted(kw.items()))
    if key not in _MK_CACHE:
        mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
        base = dict(batch=2, max_len=32, tile_w=16, t_tile=16,
                    paged=True, page=16, num_pages=5)
        base.update(kw)
        _MK_CACHE[key] = MegaKernelEngine(_mk_cfg(), mesh, **base)
    return _MK_CACHE[key]


MK_PROMPTS = [[5, 6, 7], [3, 4], [9, 10, 11, 12], [1]]


def test_megakernel_bf16_still_bit_identical():
    """The quantization machinery existing must not perturb the
    unquantized persistent lane: kv_dtype='bf16' serving tokens equal
    solo runs on a fresh engine (the pre-existing mk contract), and
    the jitted step count stays flat after warmup."""
    want = ServingEngine(_mk_engine()).generate(MK_PROMPTS,
                                                max_new_tokens=6)
    srv = ServingEngine(_mk_engine(), kv_dtype="bf16")
    assert srv.engine.k_scale is None     # bf16 = no scale tables
    got = srv.generate(MK_PROMPTS, max_new_tokens=6)
    assert got == want
    n = srv.decode_cache_size()
    srv.generate([[2, 4]], max_new_tokens=3)
    assert srv.decode_cache_size() == n, "mk decode step re-specialized"


@pytest.mark.parametrize("kvd,min_agree", [("int8", 0.7), ("fp8", 0.5)])
def test_megakernel_quant_decode_token_agreement(kvd, min_agree):
    """The converted mk-reject: int8/fp8 pools on the persistent lane
    decode token-AGREEING with the layer-path quantized contract's
    bar (fused quantize-on-write / dequantize-on-read vs the fp32
    pools), surfaced via compare_greedy, with the jit cache flat."""
    want = ServingEngine(_mk_engine()).generate(MK_PROMPTS,
                                                max_new_tokens=6)
    srv = ServingEngine(_mk_engine(kv_dtype=kvd), kv_dtype=kvd)
    got = srv.generate(MK_PROMPTS, max_new_tokens=6)
    agree = srv.compare_greedy(zip(got, want))
    st = srv.stats()
    assert st["greedy_agreement"] == agree
    assert agree >= min_agree, (kvd, agree, got, want)
    assert st["kv_dtype"] == kvd
    assert st["mk_kv_dtype"] == kvd
    n = srv.decode_cache_size()
    srv.generate([[2, 4]], max_new_tokens=3)
    assert srv.decode_cache_size() == n, "mk decode step re-specialized"


def test_megakernel_int8_capacity_ratio_gate():
    """The capacity win is planned and reported on the mk lane too:
    int8 >= 1.9x pages at fixed pool bytes vs the fp32-native pools
    (BlockManager stats + the model plan, like the layer path)."""
    srv = ServingEngine(_mk_engine(kv_dtype="int8"), kv_dtype="int8")
    pool = srv.stats()["pool"]
    assert pool["capacity_ratio_vs_native"] >= 1.9, pool
    assert srv.plan["capacity_ratio_vs_native"] >= 1.9
    assert srv.stats()["kv_bytes_per_token"] < srv.plan[
        "native_page_bytes_per_rank"] / 16


def test_megakernel_quant_knob_validation():
    """kv_dtype is an ENGINE knob on the mk lane: a serving/engine
    mismatch, a dense (non-paged) build, and a hybrid build all fail
    loudly with actionable messages."""
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    with pytest.raises(ValueError, match="kv_dtype mismatch"):
        ServingEngine(_mk_engine(), kv_dtype="int8")
    with pytest.raises(ValueError, match="paged"):
        MegaKernelEngine(_mk_cfg(), mesh, batch=2, max_len=32,
                         tile_w=16, t_tile=16, kv_dtype="int8")
    hcfg = ModelConfig.tiny_next(vocab_size=128, num_key_value_heads=4,
                                 full_attn_interval=2)
    with pytest.raises(NotImplementedError, match="hybrid"):
        MegaKernelEngine(hcfg, mesh, batch=2, max_len=32, tile_w=16,
                         t_tile=16, paged=True, page=16,
                         kv_dtype="int8")


def test_bad_kv_dtype_rejected(engine):
    with pytest.raises(ValueError, match="kv_dtype"):
        ServingEngine(engine, num_slots=2, page=PAGE, kv_dtype="int4")
