"""Mega-EP fused dispatch→GEMM→combine tests.

Reference oracle pattern: ``test/nvidia/test_ep_all2all_fused.py`` —
the fused pipeline must equal routing every token through its top-k
experts densely (``ep_a2a_utils.py`` torch oracle).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.layers import ep_moe
from triton_dist_tpu.ops.ep_a2a import ep_moe_ref
from triton_dist_tpu.ops.ep_fused import (
    create_ep_fused_context, ep_route, ep_dispatch_gemm, ep_gemm_combine,
    ep_moe_fused,
)
from triton_dist_tpu.utils.testing import spmd, assert_allclose

N = 8          # mesh size
T = 8          # tokens per rank
D = 16         # hidden
F = 16         # per-expert intermediate
E = 8          # global experts (1 per rank)
K = 2          # topk


def _rand(shape, seed, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype)


def _params(seed=0):
    kr, kg, ku, kd = jax.random.split(jax.random.PRNGKey(seed), 4)
    s = D ** -0.5
    return {
        "router": jax.random.normal(kr, (D, E)) * s,
        "w_gate": jax.random.normal(kg, (E, D, F)) * s,
        "w_up": jax.random.normal(ku, (E, D, F)) * s,
        "w_down": jax.random.normal(kd, (E, F, D)) * (F ** -0.5),
    }


def _expert_fn(params):
    def f(tok, e):
        g = tok @ params["w_gate"][e]
        u = tok @ params["w_up"][e]
        return ((jax.nn.silu(g.astype(jnp.float32))
                 * u.astype(jnp.float32)).astype(tok.dtype)
                ) @ params["w_down"][e]
    return f


def test_ep_route_slots_and_counts(tp8_ctx):
    """Routing plan: slots are a per-(rank, expert) running count and
    overflow is counted."""
    ctx = create_ep_fused_context(tp8_ctx, num_experts=E, topk=K,
                                  capacity_per_expert=2, axis="tp",
                                  block_f=F, block_d=D)
    tokens = _rand((4, D), 0)
    # Tokens 0..3 all pick expert 0 twice → slots 0..7, capacity 2.
    ids = jnp.zeros((4, K), jnp.int32)
    send, state = jax.jit(lambda t, i: ep_route(t, i, ctx))(tokens, ids)
    assert send.shape == (N, 1, 2, D)
    np.testing.assert_array_equal(
        np.asarray(state.slot_index), [[0, 1], [2, 3], [4, 5], [6, 7]])
    assert int(state.num_dropped) == 6  # 8 assignments, 2 slots
    # The two surviving tokens sit in rank-0/expert-0 slots 0 and 1.
    np.testing.assert_allclose(np.asarray(send[0, 0, 0]),
                               np.asarray(tokens[0]))
    np.testing.assert_allclose(np.asarray(send[0, 0, 1]),
                               np.asarray(tokens[0]))


def test_ep_moe_fused_vs_dense_oracle(tp8_mesh, tp8_ctx):
    """Ample capacity: the fused Mega-EP pipeline equals the dense
    oracle exactly (no drops)."""
    params = _params(1)
    tokens = _rand((N * T, D), 2)
    # capacity = T*K covers the worst case (all of a rank's assignments
    # in one (rank, expert) group).
    ctx = create_ep_fused_context(tp8_ctx, num_experts=E, topk=K,
                                  capacity_per_expert=T * K, axis="tp",
                                  block_f=F, block_d=D)

    def run(p, t):
        out, dropped = ep_moe.fwd_fused(p, t, ctx, topk=K)
        return out, dropped[None]

    f = spmd(tp8_mesh, run,
             (ep_moe.param_specs("tp"), P("tp", None)),
             (P("tp", None), P("tp")))
    out, dropped = f(params, tokens)
    assert int(np.asarray(dropped).sum()) == 0

    ids, w = ep_moe.route(params["router"], tokens, K)
    expected = ep_moe_ref(tokens, ids, w, _expert_fn(params), E)
    assert_allclose(out, expected, rtol=1e-4, atol=1e-4)


def test_ep_fused_multi_expert_per_rank():
    """E_loc > 1 exercises the per-(src, expert) sub-chunk semaphores.

    Runs on a 4-device submesh with one j-tile per GEMM: interpret-mode
    DMA callbacks are ~100 ms each on this 1-core machine, so the grid
    is kept minimal (this is a semantics test, not a perf test)."""
    import numpy as onp
    from jax.sharding import Mesh
    from triton_dist_tpu.parallel.mesh import MeshContext

    n, t, e, f = 4, 4, 8, 8   # 2 experts per rank
    mesh = Mesh(onp.array(jax.devices()[:n]), ("tp",))
    mctx = MeshContext.from_mesh(mesh)
    kg, ku, kd = jax.random.split(jax.random.PRNGKey(3), 3)
    w_gate = jax.random.normal(kg, (e, D, f)) * D ** -0.5
    w_up = jax.random.normal(ku, (e, D, f)) * D ** -0.5
    w_down = jax.random.normal(kd, (e, f, D)) * f ** -0.5
    tokens = _rand((n * t, D), 4)
    ids = jax.random.randint(jax.random.PRNGKey(5), (n * t, K), 0, e)
    w = jax.nn.softmax(_rand((n * t, K), 6), axis=-1)
    ctx = create_ep_fused_context(mctx, num_experts=e, topk=K,
                                  capacity_per_expert=t * K, axis="tp",
                                  block_f=2 * f, block_d=D)

    def run(wg, wu, wd, tk, i, ww):
        out, _ = ep_moe_fused(tk, i, ww, wg, wu, wd, ctx)
        return out

    sh = P("tp", None, None)
    fn = spmd(mesh, run,
              (sh, sh, sh, P("tp", None), P("tp", None), P("tp", None)),
              P("tp", None))
    out = fn(w_gate, w_up, w_down, tokens, ids, w)

    params = {"w_gate": w_gate, "w_up": w_up, "w_down": w_down}
    expected = ep_moe_ref(tokens, ids, w, _expert_fn(params), e)
    assert_allclose(out, expected, rtol=1e-4, atol=1e-4)


def test_ep_fused_overflow_mixture(tp8_mesh, tp8_ctx):
    """Deliberate overflow with a mixture of valid and dropped
    assignments: survivors contribute exactly, drops contribute zero,
    and the drop count is reported (round-1 advisor finding)."""
    params = _params(7)
    tokens = _rand((N * T, D), 8)
    # Everyone to expert 0 → per source rank only the first assignment
    # fits (capacity 1); its k=1 twin and all later tokens drop.
    ids = jnp.zeros((N * T, K), jnp.int32)
    w = jnp.full((N * T, K), 0.5)
    ctx = create_ep_fused_context(tp8_ctx, num_experts=E, topk=K,
                                  capacity_per_expert=1, axis="tp",
                                  block_f=F, block_d=D)

    def run(p, t, i, ww):
        out, dropped = ep_moe_fused(t, i, ww, p["w_gate"], p["w_up"],
                                    p["w_down"], ctx)
        return out, dropped[None]

    f = spmd(tp8_mesh, run,
             (ep_moe.param_specs("tp"), P("tp", None), P("tp", None),
              P("tp", None)),
             (P("tp", None), P("tp")))
    out, dropped = f(params, tokens, ids, w)
    out = np.asarray(out)
    np.testing.assert_array_equal(np.asarray(dropped),
                                  np.full(N, T * K - 1))

    exp0 = _expert_fn(params)
    per_rank_first = np.asarray(
        0.5 * exp0(tokens, 0).astype(jnp.float32))
    for r in range(N):
        # First token of each rank's shard survives with weight 0.5.
        np.testing.assert_allclose(out[r * T], per_rank_first[r * T],
                                   rtol=1e-4, atol=1e-5)
        # Every other token of that shard dropped both assignments.
        np.testing.assert_allclose(out[r * T + 1:(r + 1) * T], 0.0,
                                   atol=1e-6)


def test_ep_fused_dispatch_then_combine_identity(tp8_mesh, tp8_ctx):
    """Identity weights roundtrip: up = I (F=D), down = I, no
    activation asymmetry — isolates the two fused kernels' transport
    against slot bookkeeping."""
    ctx = create_ep_fused_context(tp8_ctx, num_experts=E, topk=K,
                                  capacity_per_expert=T * K, axis="tp",
                                  block_f=D, block_d=D)
    tokens = _rand((N * T, D), 9)
    ids = jax.random.randint(jax.random.PRNGKey(10), (N * T, K), 0, E)
    w = jax.nn.softmax(_rand((N * T, K), 11), axis=-1)
    eye = jnp.tile(jnp.eye(D)[None], (1, 1, 1))  # (E_loc=1, D, D)

    def run(t, i, ww):
        h, state = ep_dispatch_gemm(t, i, eye, ctx)
        return ep_gemm_combine(h, eye, state, ww, ctx)

    f = spmd(tp8_mesh, run,
             (P("tp", None), P("tp", None), P("tp", None)),
             P("tp", None))
    out = f(tokens, ids, w)
    expected = tokens * jnp.sum(w, axis=-1, keepdims=True)
    assert_allclose(out, expected, rtol=1e-5, atol=1e-5)
