"""Collective kernels vs XLA-collective oracles (reference test pattern:
torch collectives as the oracle, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops import (
    all_gather, all_gather_ref,
    reduce_scatter, reduce_scatter_ref,
    all_reduce, all_reduce_ref, AllReduceMethod,
    p2p_put, ppermute_ref,
)
from triton_dist_tpu.utils.testing import spmd, assert_allclose


def _rand(shape, dtype=jnp.float32, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype)


@pytest.mark.parametrize("mode", ["ring", "full_mesh"])
def test_all_gather(tp8_mesh, tp8_ctx, mode):
    x = _rand((64, 128))

    f = spmd(tp8_mesh, lambda v: all_gather(v, ctx=tp8_ctx, mode=mode),
             P("tp", None), P(None, None))
    g = spmd(tp8_mesh, lambda v: all_gather_ref(v),
             P("tp", None), P(None, None))
    assert_allclose(f(x), g(x))


def test_reduce_scatter(tp8_mesh, tp8_ctx):
    x = _rand((64, 128))  # per-shard (8,128); rs over dim0 -> (1,128)? no:
    # per-shard input must be (n*c, K): replicate the array instead.
    f = spmd(tp8_mesh, lambda v: reduce_scatter(v, ctx=tp8_ctx),
             P(None, None), P("tp", None))
    g = spmd(tp8_mesh, lambda v: reduce_scatter_ref(v),
             P(None, None), P("tp", None))
    assert_allclose(f(x), g(x), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("method",
                         [AllReduceMethod.ONE_SHOT, AllReduceMethod.TWO_SHOT])
def test_all_reduce(tp8_mesh, tp8_ctx, method):
    x = _rand((64, 128))
    # Per-shard distinct values: shard the input then treat each shard as
    # this device's contribution; compare against psum.
    f = spmd(tp8_mesh, lambda v: all_reduce(v, ctx=tp8_ctx, method=method),
             P("tp", None), P("tp", None))
    g = spmd(tp8_mesh, lambda v: all_reduce_ref(v),
             P("tp", None), P("tp", None))
    assert_allclose(f(x), g(x), rtol=1e-4, atol=1e-4)


def test_p2p_put_shift(tp8_mesh, tp8_ctx):
    x = _rand((64, 128))
    perm = [(i, (i + 1) % 8) for i in range(8)]
    f = spmd(tp8_mesh, lambda v: p2p_put(v, perm, ctx=tp8_ctx, axis="tp"),
             P("tp", None), P("tp", None))
    g = spmd(tp8_mesh, lambda v: ppermute_ref(v, perm, axis="tp"),
             P("tp", None), P("tp", None))
    assert_allclose(f(x), g(x))


def test_p2p_put_multicast_grad(tp8_mesh, tp8_ctx):
    """The custom VJP must SUM fan-in cotangents when the forward perm
    multicasts one source to several destinations (the inverse perm
    converges several edges on one rank — raced puts would drop one)."""
    x = _rand((8, 128))
    perm = [(0, 3), (0, 2), (1, 5)]   # rank 0 multicasts to 2 edges

    def loss_pallas(v):
        # Each rank seeds its own received tile's cotangent; backward
        # transport must deliver (and SUM) them at the sources.
        return jnp.sum(p2p_put(v, perm, ctx=tp8_ctx, axis="tp") ** 2)

    g_pal = spmd(tp8_mesh, lambda v: jax.grad(loss_pallas)(v),
                 P("tp", None), P("tp", None))(x)
    # Oracle (lax.ppermute rejects multicast, so hand-derived): with
    # y_dst = x_src per edge and L_dst = sum y_dst², the fan-in of
    # cotangents gives dL/dx_r = 2·outdeg(r)·x_r.
    outdeg = np.zeros((8, 1), np.float32)
    for s, _ in perm:
        outdeg[s] += 1.0
    want = 2.0 * outdeg[:, None] * np.asarray(x).reshape(8, 1, 128)
    assert_allclose(g_pal, want.reshape(np.asarray(g_pal).shape),
                    rtol=1e-5, atol=1e-5)


def test_p2p_put_partial(tp8_mesh, tp8_ctx):
    """Non-receivers must see zeros."""
    x = _rand((64, 128))
    perm = [(0, 3), (1, 2)]
    f = spmd(tp8_mesh, lambda v: p2p_put(v, perm, ctx=tp8_ctx, axis="tp"),
             P("tp", None), P("tp", None))
    g = spmd(tp8_mesh, lambda v: ppermute_ref(v, perm, axis="tp"),
             P("tp", None), P("tp", None))
    assert_allclose(f(x), g(x))


@pytest.mark.parametrize("inner,outer", [("tp", "dp"), ("dp", "tp")])
def test_all_reduce_2d(dp2tp4_mesh, dp2tp4_ctx, inner, outer):
    """Hierarchical RS->AR->AG AllReduce == flat psum over both axes
    (the INTRA/INTER CommScope decomposition; DCN carries 1/n_inner)."""
    from triton_dist_tpu.ops import all_reduce_2d

    x = _rand((32, 64), seed=9)
    f = spmd(dp2tp4_mesh,
             lambda v: all_reduce_2d(v, ctx=dp2tp4_ctx, inner_axis=inner,
                                     outer_axis=outer),
             P(None, None), P(None, None))
    g = spmd(dp2tp4_mesh,
             lambda v: jax.lax.psum(v, (outer, inner)),
             P(None, None), P(None, None))
    assert_allclose(f(x), g(x), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("mode", ["interleaved", "phased"])
@pytest.mark.parametrize("inner,outer", [("tp", "dp"), ("dp", "tp")])
def test_all_gather_2d(dp2tp4_mesh, dp2tp4_ctx, mode, inner, outer):
    """Hierarchical ICI/DCN allgather == flat gather over both axes.
    ``interleaved`` is the reference's 2D ring where outer hops hide
    under inner rings (``allgather.py:232``); both axis assignments
    exercise O=2/I=4 and O=4/I=2."""
    from triton_dist_tpu.ops import all_gather_2d

    x = _rand((64, 32), seed=40)
    f = spmd(dp2tp4_mesh,
             lambda v: all_gather_2d(v, ctx=dp2tp4_ctx,
                                     inner_axis=inner, outer_axis=outer,
                                     mode=mode),
             P(("dp", "tp"), None), P(None, None))
    g = spmd(dp2tp4_mesh,
             lambda v: jax.lax.all_gather(
                 jax.lax.all_gather(v, inner, axis=0, tiled=True),
                 outer, axis=0, tiled=True),
             P(("dp", "tp"), None), P(None, None))
    assert_allclose(f(x), g(x))


def test_race_detector_clean(tp8_mesh, tp8_ctx):
    """The interpret-mode vector-clock race detector (our analogue of
    compute-sanitizer, SURVEY.md section 5) accepts the ring allgather:
    every remote write is ordered by a semaphore wait."""
    from jax.experimental.pallas import tpu as pltpu
    from triton_dist_tpu.utils import distributed as dist

    x = _rand((32, 32), seed=41)
    orig = dist.interpret_arg

    def detect_arg():
        return pltpu.InterpretParams(dma_execution_mode="eager",
                                     detect_races=True)

    # core_call binds interpret_arg by name at import time — patch it
    # in the pallas_helpers namespace.
    from triton_dist_tpu.lang import pallas_helpers
    from jax.experimental.pallas import tpu as pltpu_mod
    import jax._src.pallas.mosaic.interpret.interpret_pallas_call as ipc

    pallas_helpers.interpret_arg = detect_arg
    pltpu_mod.reset_tpu_interpret_mode_state()
    try:
        f = spmd(tp8_mesh, lambda v: all_gather(v, ctx=tp8_ctx),
                 P("tp", None), P(None, None))
        out = f(x)
        g = spmd(tp8_mesh, lambda v: all_gather_ref(v), P("tp", None),
                 P(None, None))
        assert_allclose(out, g(x))
        # The detector only *records* races; assert the flag directly.
        assert ipc.races is not None, "race detector did not engage"
        assert not ipc.races.races_found, \
            "race detector flagged the ring allgather"
    finally:
        pallas_helpers.interpret_arg = orig


def test_all_reduce_recursive(tp8_mesh, tp8_ctx):
    """Rabenseifner recursive halving-doubling (tree-class, 2·log n
    steps) vs psum."""
    x = _rand((64, 64), seed=50)
    f = spmd(tp8_mesh,
             lambda v: all_reduce(v, ctx=tp8_ctx,
                                  method=AllReduceMethod.RECURSIVE),
             P("tp", None), P("tp", None))
    g = spmd(tp8_mesh, lambda v: all_reduce_ref(v),
             P("tp", None), P("tp", None))
    assert_allclose(f(x), g(x), rtol=1e-4, atol=1e-4)


def test_all_reduce_recursive_validation(tp8_mesh, tp8_ctx):
    import pytest as _pytest
    # (32, 64) shards evenly over 8 ranks (per-shard rows=4) but 4 is
    # not divisible by n=8 — must hit the RECURSIVE precondition, not
    # shard_map's own divisibility error.
    with _pytest.raises(ValueError, match="RECURSIVE"):
        spmd(tp8_mesh,
             lambda v: all_reduce(v, ctx=tp8_ctx,
                                  method=AllReduceMethod.RECURSIVE),
             P("tp", None), P("tp", None))(_rand((32, 64), seed=51))


def test_broadcast(tp8_mesh, tp8_ctx):
    from triton_dist_tpu.ops import broadcast, broadcast_ref

    x = _rand((64, 32), seed=60)
    for root in (0, 5):
        f = spmd(tp8_mesh,
                 lambda v: broadcast(v, root, ctx=tp8_ctx, axis="tp"),
                 P("tp", None), P("tp", None))
        g = spmd(tp8_mesh,
                 lambda v: broadcast_ref(v, root, axis="tp"),
                 P("tp", None), P("tp", None))
        assert_allclose(f(x), g(x))


@pytest.mark.parametrize("impl", ["fused", "pallas"])
def test_a2a_gemm(tp8_mesh, tp8_ctx, impl):
    from triton_dist_tpu.ops import a2a_gemm, a2a_gemm_ref

    x = _rand((64, 2, 32), seed=61)   # per-shard (8, 2, 32)
    w = _rand((32, 16), seed=62)
    f = spmd(tp8_mesh,
             lambda v, ww: a2a_gemm(v, ww, ctx=tp8_ctx, axis="tp",
                                    impl=impl),
             (P("tp", None, None), P(None, None)), P("tp", None))
    g = spmd(tp8_mesh,
             lambda v, ww: a2a_gemm_ref(v, ww, axis="tp"),
             (P("tp", None, None), P(None, None)), P("tp", None))
    assert_allclose(f(x, w), g(x, w), rtol=1e-4, atol=1e-4)


def test_a2a_gemm_fused_return_recv(tp8_mesh, tp8_ctx):
    """The fused kernel's second output is the post-A2A tensor."""
    from triton_dist_tpu.ops.a2a_gemm import (
        a2a_gemm_fused, create_a2a_gemm_context)
    from triton_dist_tpu.ops.all_to_all import all_to_all_ref

    x = _rand((64, 4, 32), seed=63)
    w = _rand((32, 16), seed=64)
    fctx = create_a2a_gemm_context(tp8_ctx, "tp")
    f = spmd(tp8_mesh,
             lambda v, ww: a2a_gemm_fused(v, ww, fctx, return_recv=True),
             (P("tp", None, None), P(None, None)),
             (P("tp", None), P("tp", None)))
    out, recv = f(x, w)
    g = spmd(tp8_mesh,
             lambda v: all_to_all_ref(v, axis="tp").reshape(-1, v.shape[-1]),
             P("tp", None, None), P("tp", None))
    assert_allclose(recv, g(x))
