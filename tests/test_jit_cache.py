"""CompiledCache + cached host-level transport wrappers
(``utils/jit_cache.py``, ``ops.p2p_put_host``, ``ops.broadcast_host``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.utils.jit_cache import CompiledCache


def test_compiled_cache_hit_and_introspection():
    cache = CompiledCache(4)
    builds = []

    def build():
        builds.append(1)
        return object()

    a = cache.get_or_build("k", build)
    assert cache.get_or_build("k", build) is a
    assert builds == [1]
    assert len(cache) == 1 and "k" in cache and cache["k"] is a
    cache.clear()
    assert len(cache) == 0


def test_compiled_cache_fifo_eviction():
    cache = CompiledCache(2)
    for k in ("a", "b", "c"):
        cache.get_or_build(k, lambda k=k: k.upper())
    assert len(cache) == 2
    assert "a" not in cache            # oldest evicted
    assert cache["b"] == "B" and cache["c"] == "C"


def test_compiled_cache_rejects_bad_size():
    with pytest.raises(ValueError):
        CompiledCache(0)


def test_host_transport_wrappers(tp8_mesh):
    """p2p_put_host / broadcast_host: correct results AND the compiled
    callable is reused (one cache entry, identical object) on repeat
    calls with the same geometry."""
    from triton_dist_tpu.ops import broadcast_host, p2p_put_host
    from triton_dist_tpu.ops.broadcast import _BCAST_HOST_CACHE
    from triton_dist_tpu.ops.p2p import _P2P_HOST_CACHE

    x = jax.device_put(
        jnp.arange(32, dtype=jnp.float32).reshape(8, 4),
        NamedSharding(tp8_mesh, P("tp", None)))
    xs = np.asarray(x)

    perm = tuple((r, (r + 1) % 8) for r in range(8))
    _P2P_HOST_CACHE.clear()
    got = np.asarray(p2p_put_host(x, perm, tp8_mesh, axis="tp"))
    want = np.zeros_like(xs)
    for s, d in perm:
        want[d] = xs[s]
    np.testing.assert_allclose(got, want)
    compiled = _P2P_HOST_CACHE[(tp8_mesh, "tp", perm, 2)]
    p2p_put_host(x, perm, tp8_mesh, axis="tp")
    assert _P2P_HOST_CACHE[(tp8_mesh, "tp", perm, 2)] is compiled
    assert len(_P2P_HOST_CACHE) == 1

    _BCAST_HOST_CACHE.clear()
    got_b = np.asarray(broadcast_host(x, 3, mesh=tp8_mesh, axis="tp"))
    np.testing.assert_allclose(got_b, np.tile(xs[3], (8, 1)))
    broadcast_host(x, 3, mesh=tp8_mesh, axis="tp")
    assert len(_BCAST_HOST_CACHE) == 1
