"""Substrate tests: notify/wait, barrier, one-sided put.

The acceptance tests for build stage 1 (SURVEY.md §7) — the analogue of
reference tutorials 01 (notify/wait) and 02 (intra-node allgather
primitive).
"""

import functools
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

import triton_dist_tpu.lang as dl
from triton_dist_tpu.lang import core_call
from triton_dist_tpu.shmem import symm_tensor, barrier_all
from triton_dist_tpu.utils.testing import spmd, assert_allclose


def test_symm_tensor(tp8_mesh):
    ws = symm_tensor(tp8_mesh, (4, 128), jnp.float32, axis="tp")
    assert ws.shape == (32, 128)
    assert ws.dtype == jnp.float32


def test_host_barrier(tp8_mesh):
    barrier_all(tp8_mesh, axis="tp")  # must simply not deadlock


def test_remote_put_ring(tp8_mesh, tp8_ctx):
    """Tutorial-01/02 analogue: every device puts its buffer to its right
    neighbour; result equals a ring shift."""

    def kernel(x_ref, out_ref, send_sem, recv_sem, *, ctx):
        n = dl.num_ranks("tp")
        me = dl.rank("tp")
        right = jax.lax.rem(me + 1, n)
        # Entry barrier: peers must be inside the kernel before any put.
        dl.barrier_tile("tp", ctx=ctx)
        copy = dl.remote_put(x_ref, out_ref, send_sem, recv_sem, right,
                             axis="tp", ctx=ctx)
        copy.wait()

    def run(x):
        return core_call(
            functools.partial(kernel, ctx=tp8_ctx),
            comm=True,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
        )(x)

    x = jnp.arange(8 * 8 * 128, dtype=jnp.float32).reshape(64, 128)
    f = spmd(tp8_mesh, run, P("tp", None), P("tp", None))
    y = f(x)
    expected = jnp.roll(x.reshape(8, 8, 128), 1, axis=0).reshape(64, 128)
    assert_allclose(y, expected)


def test_notify_wait_counter(tp8_mesh, tp8_ctx):
    """All devices notify rank 0's semaphore; rank 0 waits for n counts —
    the counting re-design of signal_wait_until (SURVEY.md §7)."""

    def kernel(out_ref, zero_v, sem, *, ctx):
        n = dl.num_ranks("tp")
        me = dl.rank("tp")
        # Align entry before signalling scratch semaphores cross-device.
        dl.barrier_all("tp", ctx=ctx)
        dl.notify(sem, 0, axis="tp", ctx=ctx)

        @pl.when(me == 0)
        def _():
            dl.wait(sem, n)

        zero_v[...] = jnp.full_like(zero_v, 7.0)
        pltpu.sync_copy(zero_v, out_ref)

    def run():
        return core_call(
            functools.partial(kernel, ctx=tp8_ctx),
            comm=True,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32),
                            pltpu.SemaphoreType.REGULAR],
        )()

    f = spmd(tp8_mesh, run, (), P("tp", None))
    y = f()
    assert_allclose(y, jnp.full((64, 128), 7.0))


def test_barrier_all_in_kernel(tp8_mesh, tp8_ctx):
    def kernel(out_ref, v, *, ctx):
        dl.barrier_all("tp", ctx=ctx)
        v[...] = jnp.ones_like(v)
        pltpu.sync_copy(v, out_ref)

    def run():
        return core_call(
            functools.partial(kernel, ctx=tp8_ctx),
            comm=True,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],
        )()

    f = spmd(tp8_mesh, run, (), P("tp", None))
    assert_allclose(f(), jnp.ones((64, 128)))


def test_logical_device_id_2d(dp2tp4_mesh, dp2tp4_ctx):
    """Ring put along tp inside a 2D (dp, tp) mesh must stay within each
    dp group — validates logical-id linearization."""

    def kernel(x_ref, out_ref, send_sem, recv_sem, *, ctx):
        n = dl.num_ranks("tp")
        me = dl.rank("tp")
        right = jax.lax.rem(me + 1, n)
        dl.barrier_tile("tp", ctx=ctx)
        copy = dl.remote_put(x_ref, out_ref, send_sem, recv_sem, right,
                             axis="tp", ctx=ctx)
        copy.wait()

    def run(x):
        return core_call(
            functools.partial(kernel, ctx=dp2tp4_ctx),
            comm=True,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
        )(x)

    x = jnp.arange(8 * 8 * 128, dtype=jnp.float32).reshape(64, 128)
    f = spmd(dp2tp4_mesh, run, P(("dp", "tp"), None), P(("dp", "tp"), None))
    y = f(x)
    blocks = x.reshape(2, 4, 8, 128)
    expected = jnp.roll(blocks, 1, axis=1).reshape(64, 128)
    assert_allclose(y, expected)


def test_race_detector_flags_sig_sem_only_consumer(tmp_path):
    """putmem_signal_block's documented caveat, enforced by a test
    (round-1 advisor finding): the remote sig_sem signal can overtake
    the bulk data, so a consumer that waits on sig_sem ALONE and then
    reads the destination is racy. The vector-clock interpreter must
    refuse to let that pass silently — it either records the race or
    aborts the run — while the correct discipline (recv_sem before the
    read) runs clean. Subprocess-isolated: the bad run can tear down
    the interpreter state.
    """
    import subprocess
    import sys
    import textwrap

    script = tmp_path / "sig_sem_probe.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
        sys.path.insert(0, %r)
        import numpy as np
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu
        from jax.sharding import Mesh, PartitionSpec as P
        import jax._src.pallas.mosaic.interpret.interpret_pallas_call as ipc
        import triton_dist_tpu.lang as dl
        from triton_dist_tpu.lang import core_call, pallas_helpers
        from triton_dist_tpu.parallel.mesh import MeshContext
        from triton_dist_tpu.utils.testing import spmd

        wait_recv_first = sys.argv[1] == "good"
        mesh = Mesh(np.array(jax.devices()[:8]), ("tp",))
        ctx = MeshContext.from_mesh(mesh)

        def kern(x_ref, o_ref, sig_sem, send_sem, recv_sem, chk_v):
            me = dl.rank("tp")
            n = dl.num_ranks("tp")
            peer = jax.lax.rem(me + 1, n)
            dl.barrier_all("tp", ctx=ctx)
            dl.putmem_signal_block(o_ref, x_ref, sig_sem, peer,
                                   send_sem, recv_sem, axis="tp",
                                   ctx=ctx)
            dl.wait(sig_sem, 1)
            if wait_recv_first:          # the documented discipline
                dl.wait_arrivals(recv_sem, x_ref, 1)
            pltpu.sync_copy(o_ref, chk_v)
            if not wait_recv_first:
                dl.wait_arrivals(recv_sem, x_ref, 1)
            dl.barrier_all("tp", ctx=ctx)

        pallas_helpers.interpret_arg = lambda: pltpu.InterpretParams(
            dma_execution_mode="eager", detect_races=True)

        def run(v):
            return core_call(
                kern, comm=True,
                out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
                out_specs=pl.BlockSpec(memory_space=pl.ANY),
                scratch_shapes=[pltpu.SemaphoreType.REGULAR(()),
                                pltpu.SemaphoreType.DMA(()),
                                pltpu.SemaphoreType.DMA(()),
                                pltpu.VMEM((8, 128), jnp.float32)])(v)

        x = jax.random.normal(jax.random.PRNGKey(0), (64, 128))
        out = spmd(mesh, run, P("tp", None), P("tp", None))(x)
        np.asarray(out)
        if ipc.races is not None and ipc.races.races_found:
            print("RACES_FOUND")
        else:
            print("CLEAN")
    """) % str(Path(__file__).resolve().parents[1]))

    def probe(mode):
        try:
            r = subprocess.run([sys.executable, str(script), mode],
                               capture_output=True, text=True,
                               timeout=240)
            return r.returncode, r.stdout
        except subprocess.TimeoutExpired:
            return -1, "TIMEOUT"

    rc, out = probe("good")
    assert rc == 0 and "CLEAN" in out, (
        f"correct discipline must run clean: rc={rc} out={out[-200:]}")
    rc, out = probe("bad")
    assert not (rc == 0 and "CLEAN" in out), (
        "sig_sem-only consumer passed silently — the race detector "
        "must flag, abort, or wedge on the protocol violation")


# ---------------------------------------------------------------------------
# Teams, getmem, fence/quiet (libshmem surface)
# ---------------------------------------------------------------------------

def test_team_queries(dp2tp4_mesh, dp2tp4_ctx):
    """team_my_pe / n_pes / translate over mesh-axis teams."""
    from triton_dist_tpu.lang import Team, team_world, team_axis

    world = team_world(dp2tp4_ctx)
    tp = team_axis(dp2tp4_ctx, "tp")
    dp = team_axis(dp2tp4_ctx, "dp")
    assert world.n_pes() == 8 and tp.n_pes() == 4 and dp.n_pes() == 2

    def probe():
        return (jnp.full((1,), world.my_pe(), jnp.int32),
                jnp.full((1,), tp.my_pe(), jnp.int32),
                jnp.full((1,), world.translate_pe(world.my_pe(), tp),
                         jnp.int32),
                jnp.full((1,), tp.translate_pe(tp.my_pe(), world),
                         jnp.int32))

    w, t, w2t, t2w = spmd(dp2tp4_mesh, probe, (),
                          (P(("dp", "tp")),) * 4)()
    # Mesh is (dp=2, tp=4) outer-major: world pe = dp*4 + tp.
    np.testing.assert_array_equal(np.asarray(w), np.arange(8))
    np.testing.assert_array_equal(np.asarray(t), np.arange(8) % 4)
    # world pe -> its tp-team pe is pe % 4; tp pe -> world pe restores.
    np.testing.assert_array_equal(np.asarray(w2t), np.arange(8) % 4)
    np.testing.assert_array_equal(np.asarray(t2w), np.arange(8))


def test_team_device_id_addresses_remote_put(dp2tp4_mesh, dp2tp4_ctx):
    """A put addressed via Team.device_id lands on the right device:
    rotate buffers along the tp team using team PE arithmetic."""
    from triton_dist_tpu.lang import team_axis

    tp = team_axis(dp2tp4_ctx, "tp")

    def kernel(x_ref, out_ref, send_sem, recv_sem):
        me = tp.my_pe()
        n = tp.n_pes()
        nxt = jax.lax.rem(me + 1, n)
        dl.barrier_tile("tp", ctx=dp2tp4_ctx)
        copy = pltpu.make_async_remote_copy(
            src_ref=x_ref, dst_ref=out_ref, send_sem=send_sem,
            recv_sem=recv_sem, device_id=tp.device_id(nxt),
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        copy.start()
        copy.wait()

    def run(x):
        return core_call(
            kernel, comm=True,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
        )(x)

    x = jnp.arange(8 * 8 * 128, dtype=jnp.float32).reshape(64, 128)
    out = spmd(dp2tp4_mesh, run, P(("dp", "tp"), None),
               P(("dp", "tp"), None))(x)
    want = np.asarray(x).reshape(2, 4, 8, 128)
    want = np.roll(want, 1, axis=1).reshape(64, 128)  # tp ring shift
    np.testing.assert_array_equal(np.asarray(out), want)


def test_getmem_block_pull_shift(tp8_mesh, tp8_ctx):
    """Symmetric pull: every rank gets (me+2)'s buffer; result equals a
    left-shift by 2 — the SPMD lockstep get realised by owner puts."""

    def kernel(x_ref, out_ref, send_sem, recv_sem):
        n = dl.num_ranks("tp")
        me = dl.rank("tp")
        peer = jax.lax.rem(me + 2, n)        # whom I pull from
        requester = jax.lax.rem(me - 2 + n, n)  # who pulls from me
        dl.barrier_all("tp", ctx=tp8_ctx)
        copy = dl.getmem_block(out_ref, x_ref, peer, requester,
                               send_sem, recv_sem, axis="tp", ctx=tp8_ctx)
        dl.quiet(copy)
        dl.wait_arrivals(recv_sem, out_ref, 1)

    def run(x):
        return core_call(
            kernel, comm=True,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
        )(x)

    x = jnp.arange(8 * 8 * 128, dtype=jnp.float32).reshape(64, 128)
    out = spmd(tp8_mesh, run, P("tp", None), P("tp", None))(x)
    want = np.roll(np.asarray(x).reshape(8, 8, 128), -2, axis=0)
    np.testing.assert_array_equal(np.asarray(out),
                                  want.reshape(64, 128))


def test_broadcastmem_in_kernel(tp8_mesh, tp8_ctx):
    """In-kernel broadcast from a non-zero root: every rank ends with
    the root's buffer (reference libshmem_device.broadcastmem)."""

    def kernel(x_ref, out_ref, send_sem, recv_sem, *, ctx):
        # No explicit barrier: the collective runs its own barrier_all
        # (scratch semaphores are unsafe under skewed kernel entry).
        dl.broadcastmem(out_ref, x_ref, 3, send_sem, recv_sem,
                        axis="tp", ctx=ctx)

    def run(x):
        return core_call(
            functools.partial(kernel, ctx=tp8_ctx),
            comm=True,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
        )(x)

    x = jnp.arange(8 * 8 * 128, dtype=jnp.float32).reshape(64, 128)
    out = spmd(tp8_mesh, run, P("tp", None), P("tp", None))(x)
    expected = jnp.tile(x[3 * 8:4 * 8], (8, 1))   # root 3's shard
    assert_allclose(out, expected)


def test_fcollect_in_kernel(tp8_mesh, tp8_ctx):
    """In-kernel flat collect: every rank gathers all 8 shards into its
    (n, rows, cols) buffer (reference libshmem_device.fcollect)."""

    def kernel(x_ref, out_ref, send_sem, recv_sem, *, ctx):
        dl.fcollect(out_ref, x_ref, send_sem, recv_sem, axis="tp",
                    ctx=ctx)

    def run(x):
        return core_call(
            functools.partial(kernel, ctx=tp8_ctx),
            comm=True,
            out_shape=jax.ShapeDtypeStruct((8,) + x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
        )(x)

    x = jnp.arange(8 * 4 * 128, dtype=jnp.float32).reshape(32, 128)
    out = spmd(tp8_mesh, run, P("tp", None), P(None, None, None))(x)
    expected = jnp.asarray(x).reshape(8, 4, 128)
    assert_allclose(out, expected)


# The reference libshmem_device surface (language/extra/
# libshmem_device.py public defs, enumerated from the source). Every
# name must resolve on lang.shmem_device — as a real implementation, a
# documented granularity-collapse alias, or a documented-impossible
# stub that raises NotImplementedError with the TPU redesign pointer.
_REFERENCE_LIBSHMEM_SURFACE = [
    "barrier", "barrier_all", "barrier_all_block", "barrier_all_vec",
    "barrier_all_warp", "barrier_all_wave", "barrier_all_wg",
    "barrier_block", "barrier_warp",
    "broadcast", "broadcast_block", "broadcast_warp",
    "broadcastmem", "broadcastmem_block", "broadcastmem_warp",
    "fcollect", "fcollect_block", "fcollect_warp",
    "fcollectmem", "fcollectmem_block", "fcollectmem_warp",
    "fence",
    "getmem", "getmem_block", "getmem_nbi", "getmem_nbi_block",
    "getmem_nbi_warp", "getmem_nbi_wave", "getmem_nbi_wg",
    "getmem_warp", "getmem_wave", "getmem_wg",
    "int_p", "my_pe", "n_pes",
    "putmem", "putmem_block", "putmem_nbi", "putmem_nbi_block",
    "putmem_nbi_warp", "putmem_nbi_wave", "putmem_nbi_wg",
    "putmem_rma", "putmem_rma_block", "putmem_rma_nbi",
    "putmem_rma_nbi_block", "putmem_rma_nbi_warp", "putmem_rma_warp",
    "putmem_signal", "putmem_signal_block", "putmem_signal_nbi",
    "putmem_signal_nbi_block", "putmem_signal_nbi_warp",
    "putmem_signal_nbi_wave", "putmem_signal_nbi_wg",
    "putmem_signal_rma", "putmem_signal_rma_block",
    "putmem_signal_rma_nbi", "putmem_signal_rma_nbi_block",
    "putmem_signal_rma_nbi_warp", "putmem_signal_rma_warp",
    "putmem_signal_warp", "putmem_signal_wave", "putmem_signal_wg",
    "putmem_warp", "putmem_wave", "putmem_wg",
    "quiet", "quiet_pe",
    "remote_mc_ptr", "remote_ptr", "set_rocshmem_ctx",
    "signal_op", "signal_wait_until",
    "sync_all", "sync_all_block", "sync_all_warp",
    "team_my_pe", "team_n_pes", "team_sync_block", "team_sync_warp",
    "team_translate_pe",
    "uint64_wait_until_equals", "ulong_put_signal",
]

_DOCUMENTED_IMPOSSIBLE = {"remote_ptr", "remote_mc_ptr",
                          "set_rocshmem_ctx"}


def test_libshmem_surface_parity():
    from triton_dist_tpu.lang import shmem_device

    for name in _REFERENCE_LIBSHMEM_SURFACE:
        fn = getattr(shmem_device, name, None)
        assert callable(fn), f"missing libshmem surface name: {name}"
        assert name in shmem_device.__all__, f"{name} not exported"
    # The impossible trio must raise with a redesign pointer, not exist
    # as silent no-ops.
    with pytest.raises(NotImplementedError):
        shmem_device.remote_ptr(None, 0)
    with pytest.raises(NotImplementedError):
        shmem_device.remote_mc_ptr(None, None)
    with pytest.raises(NotImplementedError):
        shmem_device.set_rocshmem_ctx(None)
    # __all__ itself must resolve (catches stale export lists).
    for name in shmem_device.__all__:
        assert hasattr(shmem_device, name), f"__all__ lists {name}"


def test_team_barrier_in_kernel(dp2tp4_mesh, dp2tp4_ctx):
    """barrier(team) over the tp team: all four tp peers of each dp
    group must pass it; completion proves the team-scoped signal/wait
    count is balanced."""
    from triton_dist_tpu.lang import team_axis

    tp = team_axis(dp2tp4_ctx, "tp")

    def kernel(out_ref, v):
        dl.barrier(tp)
        v[...] = jnp.ones_like(v)
        pltpu.sync_copy(v, out_ref)

    def run():
        return core_call(
            kernel, comm=True,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],
        )()

    out = spmd(dp2tp4_mesh, run, (), P(("dp", "tp"), None))()
    assert_allclose(out, jnp.ones((64, 128)))


def test_int_p_single_word(tp8_mesh, tp8_ctx):
    """int_p ships one word to the right neighbour's slot."""

    def kernel(out_ref, staging, send_sem, recv_sem, *, ctx):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")
        right = jax.lax.rem(me + 1, n)
        dl.barrier_tile("tp", ctx=ctx)
        copy = dl.int_p(out_ref, 7, staging, right, send_sem, recv_sem,
                        axis="tp", ctx=ctx)
        copy.wait()

    def run():
        return core_call(
            functools.partial(kernel, ctx=tp8_ctx),
            comm=True,
            out_shape=jax.ShapeDtypeStruct((1, 128), jnp.int32),
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.VMEM((1, 128), jnp.int32),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
        )()

    out = spmd(tp8_mesh, run, (), P("tp", None))()
    np.testing.assert_array_equal(np.asarray(out),
                                  np.full((8, 128), 7, np.int32))
