"""Substrate tests: notify/wait, barrier, one-sided put.

The acceptance tests for build stage 1 (SURVEY.md §7) — the analogue of
reference tutorials 01 (notify/wait) and 02 (intra-node allgather
primitive).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

import triton_dist_tpu.lang as dl
from triton_dist_tpu.lang import core_call
from triton_dist_tpu.shmem import symm_tensor, barrier_all
from triton_dist_tpu.utils.testing import spmd, assert_allclose


def test_symm_tensor(tp8_mesh):
    ws = symm_tensor(tp8_mesh, (4, 128), jnp.float32, axis="tp")
    assert ws.shape == (32, 128)
    assert ws.dtype == jnp.float32


def test_host_barrier(tp8_mesh):
    barrier_all(tp8_mesh, axis="tp")  # must simply not deadlock


def test_remote_put_ring(tp8_mesh, tp8_ctx):
    """Tutorial-01/02 analogue: every device puts its buffer to its right
    neighbour; result equals a ring shift."""

    def kernel(x_ref, out_ref, send_sem, recv_sem, *, ctx):
        n = dl.num_ranks("tp")
        me = dl.rank("tp")
        right = jax.lax.rem(me + 1, n)
        # Entry barrier: peers must be inside the kernel before any put.
        dl.barrier_tile("tp", ctx=ctx)
        copy = dl.remote_put(x_ref, out_ref, send_sem, recv_sem, right,
                             axis="tp", ctx=ctx)
        copy.wait()

    def run(x):
        return core_call(
            functools.partial(kernel, ctx=tp8_ctx),
            comm=True,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
        )(x)

    x = jnp.arange(8 * 8 * 128, dtype=jnp.float32).reshape(64, 128)
    f = spmd(tp8_mesh, run, P("tp", None), P("tp", None))
    y = f(x)
    expected = jnp.roll(x.reshape(8, 8, 128), 1, axis=0).reshape(64, 128)
    assert_allclose(y, expected)


def test_notify_wait_counter(tp8_mesh, tp8_ctx):
    """All devices notify rank 0's semaphore; rank 0 waits for n counts —
    the counting re-design of signal_wait_until (SURVEY.md §7)."""

    def kernel(out_ref, zero_v, sem, *, ctx):
        n = dl.num_ranks("tp")
        me = dl.rank("tp")
        # Align entry before signalling scratch semaphores cross-device.
        dl.barrier_all("tp", ctx=ctx)
        dl.notify(sem, 0, axis="tp", ctx=ctx)

        @pl.when(me == 0)
        def _():
            dl.wait(sem, n)

        zero_v[...] = jnp.full_like(zero_v, 7.0)
        pltpu.sync_copy(zero_v, out_ref)

    def run():
        return core_call(
            functools.partial(kernel, ctx=tp8_ctx),
            comm=True,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32),
                            pltpu.SemaphoreType.REGULAR],
        )()

    f = spmd(tp8_mesh, run, (), P("tp", None))
    y = f()
    assert_allclose(y, jnp.full((64, 128), 7.0))


def test_barrier_all_in_kernel(tp8_mesh, tp8_ctx):
    def kernel(out_ref, v, *, ctx):
        dl.barrier_all("tp", ctx=ctx)
        v[...] = jnp.ones_like(v)
        pltpu.sync_copy(v, out_ref)

    def run():
        return core_call(
            functools.partial(kernel, ctx=tp8_ctx),
            comm=True,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],
        )()

    f = spmd(tp8_mesh, run, (), P("tp", None))
    assert_allclose(f(), jnp.ones((64, 128)))


def test_logical_device_id_2d(dp2tp4_mesh, dp2tp4_ctx):
    """Ring put along tp inside a 2D (dp, tp) mesh must stay within each
    dp group — validates logical-id linearization."""

    def kernel(x_ref, out_ref, send_sem, recv_sem, *, ctx):
        n = dl.num_ranks("tp")
        me = dl.rank("tp")
        right = jax.lax.rem(me + 1, n)
        dl.barrier_tile("tp", ctx=ctx)
        copy = dl.remote_put(x_ref, out_ref, send_sem, recv_sem, right,
                             axis="tp", ctx=ctx)
        copy.wait()

    def run(x):
        return core_call(
            functools.partial(kernel, ctx=dp2tp4_ctx),
            comm=True,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
        )(x)

    x = jnp.arange(8 * 8 * 128, dtype=jnp.float32).reshape(64, 128)
    f = spmd(dp2tp4_mesh, run, P(("dp", "tp"), None), P(("dp", "tp"), None))
    y = f(x)
    blocks = x.reshape(2, 4, 8, 128)
    expected = jnp.roll(blocks, 1, axis=1).reshape(64, 128)
    assert_allclose(y, expected)
