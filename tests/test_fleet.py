"""Fleet-scale serving battery: the FleetRouter over R replicated
serving fleets — prefix-affinity routing vs the round-robin baseline,
cross-fleet session failover (parked-tier handoff AND deterministic
re-prefill, both token-exact vs ``Engine.serve``), drain/restore
autoscale with in-flight sessions, deterministic saturation spillover,
shed-by-deadline-class graceful degradation, the fleet chaos soak, and
the fleet invariant checker's own teeth (docs/serving.md, "Fleet
serving").

Everything is seeded and runs on the CPU mesh; all fleets share one
module-scoped layer Engine (weights + jit prefill), each with its own
pools, scheduler, and tier store — exactly the replicated-fleet shape.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from triton_dist_tpu.models import Engine, ModelConfig
from triton_dist_tpu.resilience import chaos
from triton_dist_tpu.resilience.policy import RetryPolicy
from triton_dist_tpu.serving import (
    FleetRouter, QueueFullError, Request, ServingEngine, ShedError,
    heavy_tail_trace,
)
from triton_dist_tpu.serving.tiers import extend_session

CFG = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                       intermediate_size=32, num_hidden_layers=2,
                       num_attention_heads=4, num_key_value_heads=4,
                       head_dim=8)
MAX_LEN = 32


@pytest.fixture(scope="module")
def engine():
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    return Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=0)


def _oracle(engine, prompt, gen_len):
    ids = jnp.asarray(np.asarray([list(prompt)], np.int32))
    return np.asarray(engine.serve(ids, gen_len=gen_len))[0].tolist()


def _factory(engine, **kw):
    """One fleet: a ServingEngine with its own pools + tier store."""
    def make():
        args = dict(num_slots=2, page=4, num_pages=16,
                    prefix_reuse=True, kv_tiers={"host_pages": 128})
        args.update(kw)
        return ServingEngine(engine, **args)
    return make


def _run_until_decoding(router, h):
    """Step until ``h`` is running with at least one emitted token
    (the parked-handoff failover precondition)."""
    for _ in range(200):
        if h.status == "running" and h.tokens:
            return
        router.step()
    raise AssertionError(f"{h.request.request_id} never started "
                         f"decoding ({h.status})")


# ---------------------------------------------------------------------------
# Routing: affinity vs round-robin, spillover determinism
# ---------------------------------------------------------------------------

def _serve_trace(router, n_events=30, seed=5):
    events = heavy_tail_trace(n_events, n_sessions=40, vocab=64,
                              seed=seed, zipf_a=1.2,
                              turn_tokens=(4, 8), max_total=16)
    history = {}
    for ev in events:
        prompt = extend_session(history, ev, max_prompt=16)
        h = router.submit(prompt, max_new_tokens=ev["gen"])
        router.run()
        extend_session(history, ev, reply=h.tokens)
    return router.stats()


def test_affinity_routing_beats_round_robin(engine):
    """Same seeded multi-turn trace, two routers: prefix-affinity
    routing must land strictly more prefix hits than the round-robin
    spread (same-session turns keep hitting the fleet that holds
    their pages)."""
    st_aff = _serve_trace(FleetRouter(_factory(engine), fleets=2,
                                      affinity=True))
    st_rr = _serve_trace(FleetRouter(_factory(engine), fleets=2,
                                     affinity=False))
    assert st_aff["kv_hot_hit_rate"] is not None
    assert st_aff["kv_hot_hit_rate"] > (st_rr["kv_hot_hit_rate"] or 0.0)
    assert st_aff["affinity_hits"] > 0
    assert st_aff["router_affinity_hit_rate"] > 0
    # Round-robin records no affinity hits by construction.
    assert st_rr["affinity_hits"] == 0


def test_routing_is_token_exact_and_jit_flat(engine):
    router = FleetRouter(_factory(engine), fleets=2)
    prompts = [[1, 2, 3, 4, 5, 6, 7, 8], [9, 8, 7], [1, 2, 3, 4, 9]]
    got = router.generate(prompts, max_new_tokens=5)
    for p, toks in zip(prompts, got):
        assert toks == _oracle(engine, p, 5)
    # The fleet-wide no-recompilation gate: every fleet's decode
    # dispatch holds exactly one jit entry with routing active.
    assert router.decode_cache_sizes() == [1, 1]


def test_saturation_spillover_is_deterministic(engine):
    """A burst past one fleet's queue spills onto the next in a fully
    deterministic order: two identical routers assign every request
    to the same fleet."""
    prefix = [1, 2, 3, 4]                    # one full page key

    def assignments():
        router = FleetRouter(
            _factory(engine, num_slots=1, max_queue=2), fleets=2)
        # Seed the prefix on one fleet so affinity PREFERS it...
        router.generate([prefix + [9]], max_new_tokens=2)
        # ...then burst more same-prefix traffic than it can queue.
        hs = [router.submit(prefix + [i + 1], max_new_tokens=2)
              for i in range(6)]
        placed = [router._fleet_of(h).id if router._fleet_of(h)
                  else None for h in hs]
        st = router.stats()
        router.run()
        for h in hs:
            assert h.status == "done"
        return placed, st["spillovers"]

    a1, spill1 = assignments()
    a2, spill2 = assignments()
    assert a1 == a2
    assert spill1 == spill2 and spill1 > 0
    # The burst overflowed the preferred fleet onto the other one.
    assert len(set(x for x in a1 if x is not None)) == 2


def test_router_queue_and_admission_shed(engine):
    """Everything saturated: interactive submissions get backpressure
    (QueueFullError), batch-class ones shed terminally — admission
    control degrades by deadline class instead of failing broadly."""
    router = FleetRouter(
        _factory(engine, num_slots=1, max_queue=2), fleets=2,
        max_queue=0)
    # Fill both fleet queues (placement is queue-side until a tick).
    hs = [router.submit([i + 1, 2], max_new_tokens=2)
          for i in range(4)]
    batch = router.submit([9, 9, 9], max_new_tokens=2)
    assert batch.status == "shed" and batch.done
    assert isinstance(batch.error, ShedError)
    with pytest.raises(QueueFullError):
        router.submit(Request(prompt=[8, 8], max_new_tokens=2,
                              deadline=1e9))
    st = router.stats()
    assert st["shed_requests"] == 1
    router.run()
    for h in hs:
        assert h.status == "done"


# ---------------------------------------------------------------------------
# Fleet failover: both cross-fleet paths, token-exact
# ---------------------------------------------------------------------------

def test_fleet_kill_parked_handoff_token_exact(engine):
    """A reachable dead fleet's running session parks into its tier,
    the pinned payload hops to a survivor, and the session resumes
    there TOKEN-EXACT (the cross-fleet tier path)."""
    router = FleetRouter(_factory(engine), fleets=2)
    prompt = [5, 5, 5, 5, 5, 5, 5, 5]
    h = router.submit(prompt, max_new_tokens=8)
    _run_until_decoding(router, h)
    victim = router._fleet_of(h)
    assert router.kill_fleet(victim.id, reachable=True)
    chaos.check_fleet_invariants(router, [h])
    router.run()
    assert h.status == "done"
    assert h.tokens == _oracle(engine, prompt, 8)
    st = router.stats()
    assert st["failover_resumed"] >= 1
    assert st["fleet_failovers"] == 1
    assert st["dead_fleets"] == 1 and st["live_fleets"] == 1


def test_fleet_kill_reprefill_token_exact(engine):
    """An UNREACHABLE dead fleet's sessions re-enter via the
    deterministic re-prefill contract on the adoptive fleet — equally
    token-exact, no tier payload needed."""
    router = FleetRouter(_factory(engine), fleets=2)
    prompt = [6, 6, 6, 1, 2, 3]
    h = router.submit(prompt, max_new_tokens=8)
    other = router.submit([4, 4, 4], max_new_tokens=4)
    _run_until_decoding(router, h)
    victim = router._fleet_of(h)
    router.kill_fleet(victim.id, reachable=False)
    chaos.check_fleet_invariants(router, [h, other])
    router.run()
    assert h.status == "done"
    assert h.tokens == _oracle(engine, prompt, 8)
    assert other.status == "done"
    assert other.tokens == _oracle(engine, [4, 4, 4], 4)
    assert router.stats()["failover_resumed"] == 0
    assert router.stats()["failover_reprefilled"] >= 1


def test_kill_fleet_guards(engine):
    router = FleetRouter(_factory(engine), fleets=2)
    router.kill_fleet(0)
    # A dead fleet kills idempotently; the last live fleet never.
    assert router.kill_fleet(0) is False
    with pytest.raises(ValueError, match="last live fleet"):
        router.kill_fleet(1)
    with pytest.raises(ValueError, match="no fleet"):
        router.kill_fleet(99)


def test_route_faults_strike_health_into_failover(engine):
    """Hard fleet_route faults strike the targeted fleet's health;
    crossing the threshold fails it over and the request still lands
    (the router never fails broadly on a link fault)."""
    from triton_dist_tpu.resilience import faults

    router = FleetRouter(_factory(engine), fleets=2,
                         fleet_fail_threshold=2)
    plan = faults.FaultPlan(
        name="drop-route",
        faults=(faults.Fault("fail_call", op="fleet_route", k=None),))
    with faults.inject(plan):
        h1 = router.submit([1, 2, 3], max_new_tokens=2)
        h2 = router.submit([4, 5, 6], max_new_tokens=2)
    # Every send faulted: both requests fell into the router queue;
    # strikes accumulated (2 per submit across both fleets).
    assert len(router.queue) == 2
    st = router.stats()
    # Both fleets were struck to the threshold, but the router keeps
    # at least one fleet serving (fail-soft, never dead-everything).
    assert st["live_fleets"] >= 1
    router.run()
    assert h1.status == "done" and h2.status == "done"
    assert h1.tokens == _oracle(engine, [1, 2, 3], 2)


# ---------------------------------------------------------------------------
# Drain / restore autoscale
# ---------------------------------------------------------------------------

def test_scale_round_trip_with_inflight_sessions(engine):
    """Scale 2→3→1 with sessions mid-decode: drained fleets park
    their running sessions, the checkpoint+tier snapshot carries the
    payloads onto the new topology, and every request finishes
    token-exact with its original handle."""
    router = FleetRouter(_factory(engine), fleets=2)
    hs = [router.submit([i + 1, 2, 3, 4, 5], max_new_tokens=6)
          for i in range(4)]
    for _ in range(2):
        router.step()
    assert router.scale_to(3) == []
    assert len(router._live_fleets()) == 3
    h_live = router.submit([7, 7, 7, 7, 7, 7], max_new_tokens=8)
    _run_until_decoding(router, h_live)
    snaps = router.scale_to(1)
    assert len(snaps) == 2
    assert len(router._live_fleets()) == 1
    for snap in snaps:
        assert snap["meta"]["format"] == "tdt-serving-ckpt-v1"
    chaos.check_fleet_invariants(router, hs + [h_live])
    router.run()
    for i, h in enumerate(hs):
        assert h.status == "done"
        assert h.tokens == _oracle(engine, [i + 1, 2, 3, 4, 5], 6)
    assert h_live.status == "done"
    assert h_live.tokens == _oracle(engine, [7, 7, 7, 7, 7, 7], 8)
    st = router.stats()
    assert st["scale_ups"] == 1 and st["scale_downs"] == 2
    assert st["drain_resumed"] >= 1      # the snapshot-payload path
    assert router.decode_cache_sizes() == [1]


def test_scale_down_without_tiers_finishes_inflight(engine):
    """No tier store: drain cannot park, so in-flight sessions FINISH
    on the draining fleet before its snapshot (park-or-finish)."""
    router = FleetRouter(_factory(engine, kv_tiers=None),
                         fleets=2, affinity=False)
    hs = [router.submit([i + 1, 9], max_new_tokens=3)
          for i in range(3)]
    router.step()
    router.scale_to(1)
    router.run()
    for i, h in enumerate(hs):
        assert h.status == "done"
        assert h.tokens == _oracle(engine, [i + 1, 9], 3)


def test_user_parked_session_stays_parked_across_failover(engine):
    """A session the CALLER parked is a deliberate suspension: a
    reachable fleet kill hops its pinned payload to a survivor but
    does NOT resume it — a later ``router.resume(h)`` finds it parked
    there and reactivates token-exact."""
    router = FleetRouter(_factory(engine), fleets=2)
    prompt = [3, 1, 4, 1, 5, 9]
    h = router.submit(prompt, max_new_tokens=8)
    _run_until_decoding(router, h)
    victim = router._fleet_of(h)
    router.park(h)
    assert h.status == "parked"
    assert router.kill_fleet(victim.id, reachable=True)
    chaos.check_fleet_invariants(router, [h])
    assert h.status == "parked"        # the suspension survived
    router.run()                       # ...and does not block drain
    assert h.status == "parked"
    assert router.stats()["parked_sessions"] == 1
    router.resume(h)
    router.run()
    assert h.status == "done"
    assert h.tokens == _oracle(engine, prompt, 8)


def test_drain_restores_user_parked_session_parked(engine):
    """``scale_to`` preserves a caller-parked session AS PARKED on the
    surviving topology (payload from the drain snapshot); resume is
    still the caller's verb."""
    router = FleetRouter(_factory(engine), fleets=2)
    filler = router.submit([9, 9, 9], max_new_tokens=4)   # loads f0
    prompt = [2, 7, 1, 8, 2, 8]
    h = router.submit(prompt, max_new_tokens=8)           # lands f1
    _run_until_decoding(router, h)
    router.park(h)
    # Guard against vacuousness: h must sit on the fleet scale_to(1)
    # will drain (the highest-id live fleet).
    assert router._fleet_of(h) is router._live_fleets()[-1]
    router.scale_to(1)
    assert h.status == "parked"        # moved, not resumed
    chaos.check_fleet_invariants(router, [h, filler])
    router.run()
    assert h.status == "parked"
    router.resume(h)
    router.run()
    assert h.status == "done"
    assert h.tokens == _oracle(engine, prompt, 8)
    assert filler.status == "done"


def test_drain_never_sheds_under_saturation(engine):
    """A voluntary ``scale_to`` must never terminate traffic: with the
    survivor's queue AND the router queue full, the drained backlog
    force-queues on the router (past ``max_queue``) instead of
    shedding — every request still completes."""
    router = FleetRouter(_factory(engine, num_slots=1, max_queue=1,
                                  kv_tiers=None),
                         fleets=2, max_queue=0, affinity=False)
    hs = [router.submit([i + 1, 2], max_new_tokens=2)    # batch class
          for i in range(2)]                 # one per fleet queue
    router.scale_to(1)
    assert router.stats()["shed_requests"] == 0
    chaos.check_fleet_invariants(router, hs)
    router.run()
    for i, h in enumerate(hs):
        assert h.status == "done"
        assert h.tokens == _oracle(engine, [i + 1, 2], 2)
    assert router.stats()["shed_requests"] == 0


# ---------------------------------------------------------------------------
# Shed by deadline class
# ---------------------------------------------------------------------------

def test_failover_sheds_batch_class_before_interactive(engine):
    """Fleet loss with the survivor saturated: the victim's queued
    backlog rehomes interactive-first, and what cannot fit sheds —
    the BATCH class, never the interactive one (deadline-class
    ordering)."""
    router = FleetRouter(
        _factory(engine, num_slots=1, max_queue=6), fleets=2,
        affinity=False, max_queue=0)
    far = 1e9
    # Round-robin rotation alternates fleets per submit; a period-4
    # class pattern puts 2 batch + 2 interactive on EACH fleet.
    batch, interactive = [], []
    for i in range(8):
        if i % 4 >= 2:
            interactive.append(router.submit(
                Request(prompt=[i + 1, 2], max_new_tokens=2,
                        deadline=far)))
        else:
            batch.append(router.submit([i + 1, 2], max_new_tokens=2))
    live = [h for h in batch + interactive if not h.done]
    victims = {f.id: [] for f in router.fleets}
    for h in live:
        f = router._fleet_of(h)
        if f is not None:
            victims[f.id].append(h)
    # Kill fleet 0: its backlog must rehome onto fleet 1's bounded
    # queue — interactive first, batch shed when full.
    router.kill_fleet(0, reachable=True)
    shed = [h for h in live if h.status == "shed"]
    assert shed, "saturated failover shed nothing"
    assert all(h.request.deadline is None for h in shed), (
        "an interactive request was shed while batch survived")
    assert all(h.status != "shed" for h in interactive)
    chaos.check_fleet_invariants(router, live)
    router.run()
    for h in interactive:
        assert h.status == "done"
    st = router.stats()
    assert st["shed_requests"] == len(shed)
    # Shed is its own verdict — never counted as a failure.
    assert st["failed"] == 0


# ---------------------------------------------------------------------------
# The fleet invariant checker's own teeth
# ---------------------------------------------------------------------------

def _small_router(engine):
    router = FleetRouter(_factory(engine), fleets=2)
    h = router.submit([1, 2, 3, 4], max_new_tokens=4)
    router.step()
    return router, h


def test_checker_passes_on_healthy_router(engine):
    router, h = _small_router(engine)
    chaos.check_fleet_invariants(router, [h])
    router.run()
    chaos.check_fleet_invariants(router, [h])


def test_checker_catches_double_ownership(engine):
    from triton_dist_tpu.serving.scheduler import RequestHandle

    router, _ = _small_router(engine)
    dup = RequestHandle(request=Request(prompt=[1, 2],
                                        request_id="dup"))
    for f in router.fleets:
        f.engine.sched.queue.append(dup)
    with pytest.raises(chaos.InvariantViolation, match="owned by BOTH"):
        chaos.check_fleet_invariants(router, [dup])


def test_checker_catches_session_on_two_fleets(engine):
    router, _ = _small_router(engine)
    k, v = (np.zeros((2, 1, 4, 4, 8), np.float32),) * 2
    for f in router.fleets:
        f.engine.tiers.put(("session", "dup"), (k, v), pages=1,
                           pinned=True)
    with pytest.raises(chaos.InvariantViolation, match="pinned on BOTH"):
        chaos.check_fleet_invariants(router)


def test_checker_catches_health_liveness_drift(engine):
    router, _ = _small_router(engine)
    router.fleets[1].health.declare_dead("drift")
    with pytest.raises(chaos.InvariantViolation,
                       match="failover skipped"):
        chaos.check_fleet_invariants(router)


def test_checker_catches_drain_gate_breach(engine):
    from triton_dist_tpu.serving.scheduler import RequestHandle

    router, _ = _small_router(engine)
    f = router.fleets[1]
    f.draining = True
    f.engine.sched.queue.append(RequestHandle(
        request=Request(prompt=[1], request_id="sneak")))
    with pytest.raises(chaos.InvariantViolation, match="drain gate"):
        chaos.check_fleet_invariants(router)


def test_checker_catches_lost_request(engine):
    router, _ = _small_router(engine)
    from triton_dist_tpu.serving.scheduler import RequestHandle

    ghost = RequestHandle(request=Request(prompt=[1],
                                          request_id="ghost"))
    with pytest.raises(chaos.InvariantViolation, match="lost"):
        chaos.check_fleet_invariants(router, [ghost])


# ---------------------------------------------------------------------------
# Router-time predictive prefetch rides routing
# ---------------------------------------------------------------------------

def test_router_prefetch_warms_tier_payloads(engine):
    """Routing a same-prefix request fires the chosen fleet's
    tier_prefetch: the transfer runs at ROUTE time and admission
    consumes the warm payload without a second tier hop."""
    router = FleetRouter(_factory(engine), fleets=2)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    router.generate([prompt], max_new_tokens=4)
    fleet = max(router.fleets,
                key=lambda f: f.engine.manager.stats["allocs"])
    eng = fleet.engine
    eng.manager.evict(len(eng.manager._prefix))
    assert len(eng.tiers) >= 2
    gets0 = eng.tiers.stats()["gets"]
    h = router.submit(prompt, max_new_tokens=4)
    # The route-time prefetch already ran the transfers.
    assert eng.stats_counters["router_prefetched_pages"] >= 2
    gets_at_route = eng.tiers.stats()["gets"] - gets0
    router.run()
    assert eng.tiers.stats()["gets"] - gets0 == gets_at_route, (
        "admission re-transferred despite the route-time warm buffer")
    assert h.tokens == _oracle(engine, prompt, 4)


# ---------------------------------------------------------------------------
# Stats / spans
# ---------------------------------------------------------------------------

def test_router_stats_and_spans(engine):
    router = FleetRouter(_factory(engine), fleets=2,
                         telemetry="spans")
    hs = [router.submit([i + 1, 2, 3, 4, 5], max_new_tokens=4)
          for i in range(3)]
    _run_until_decoding(router, hs[0])
    router.kill_fleet(router._fleet_of(hs[0]).id, reachable=True)
    router.run()
    router.scale_to(2)
    router.scale_to(1)
    st = router.stats()
    for key in ("routed", "router_affinity_hit_rate", "shed_requests",
                "fleet_failovers", "failover_resumed", "queue_depth",
                "kv_hot_hit_rate", "fleet_ttft_ms", "latency",
                "fleets", "live_fleets"):
        assert key in st
    assert st["routed"] == 3
    assert len(st["fleets"]) == len(router.fleets)
    ops = (st["latency"] or {}).get("ops", {})
    assert "route" in ops and ops["route"]["count"] == 3
    for kind in ("fleet_failover", "drain", "restore_fleet"):
        assert kind in ops, f"span kind {kind} missing from latency"
    kinds = {s.kind for s in router.obs.log.spans()}
    assert {"route", "fleet_failover", "drain",
            "restore_fleet"} <= kinds
    # Fleet-wide TTFT merges per-fleet histograms.
    assert st["fleet_ttft_ms"] is not None
    assert st["fleet_ttft_ms"]["count"] == 3


def test_router_rejects_bad_construction(engine):
    with pytest.raises(ValueError, match="prefix_reuse"):
        FleetRouter(_factory(engine, prefix_reuse=False,
                             kv_tiers=None), fleets=1)
    with pytest.raises(ValueError, match="fleets must be"):
        FleetRouter(_factory(engine), fleets=0)
    with pytest.raises(TypeError, match="RetryPolicy"):
        FleetRouter(_factory(engine), fleets=1, retry={"fleet_route":
                                                       object()})
    calls = {"n": 0}

    def mismatched():
        calls["n"] += 1
        return ServingEngine(engine, num_slots=2,
                             page=4 if calls["n"] == 1 else 8,
                             prefix_reuse=True)

    with pytest.raises(ValueError, match="identically planned"):
        FleetRouter(mismatched, fleets=2, affinity=False)


# ---------------------------------------------------------------------------
# The fleet chaos soak
# ---------------------------------------------------------------------------

def _soak_factory(engine):
    def make():
        return ServingEngine(engine, num_slots=2, page=4, num_pages=16,
                             prefix_reuse=True,
                             kv_tiers={"host_pages": 64},
                             retry=RetryPolicy(max_attempts=2))
    return make


def test_fleet_soak_mini_run(engine):
    rep = chaos.run_fleet_soak(
        _soak_factory(engine), fleets=2, seed=3, ticks=40, n_faults=6,
        router_kw={"retry": RetryPolicy(max_attempts=2)},
        scale_at=(20, 3))
    assert rep.survived_faults == rep.faults_injected == 6
    assert rep.invariant_checks >= rep.ticks
    assert rep.requests["submitted"] == sum(
        rep.requests[k] for k in ("done", "failed", "timeout", "shed"))
    assert rep.token_exact_requests == rep.requests["done"] > 0
    assert rep.scaled_at == 20


@pytest.mark.slow
def test_fleet_soak_acceptance(engine):
    """The acceptance soak (scripts/fleet_smoke.sh): ≥200 ticks, 12
    seeded faults across kills / route / handoff / tier families over
    3 fleets with a mid-soak autoscale, per-tick fleet invariants,
    every request terminal, done requests token-exact."""
    rep = chaos.run_fleet_soak(
        _soak_factory(engine), fleets=3, seed=7, ticks=200,
        n_faults=12,
        router_kw={"retry": RetryPolicy(max_attempts=2)},
        scale_at=(120, 2))
    assert rep.survived_faults >= 10
    assert rep.invariant_checks >= 200
    assert rep.token_exact_requests == rep.requests["done"] > 0
    assert rep.requests["submitted"] == sum(
        rep.requests[k] for k in ("done", "failed", "timeout", "shed"))
