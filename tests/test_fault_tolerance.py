"""Fault-tolerant serving battery: retry/backoff, prefill-worker
failover, and checkpoint/restore.

The escalation ladder under test (docs/resilience.md, "Failure
semantics"): a transient migration/chunk fault is RETRIED (absorbed,
request unaffected); exhausted retries FAIL ONE request with zero
leaked pages; consecutive post-retry failures declare the prefill
worker dead and FAIL OVER — in-flight requests requeue and finish
token-exact on the surviving role. checkpoint()/restore() round-trips
the full serving state (pools + scales bit-exact, allocator,
queue/slots, counters) and resumes decode token-exact mid-stream.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from triton_dist_tpu.models import Engine, ModelConfig, dense
from triton_dist_tpu.resilience import chaos, faults
from triton_dist_tpu.resilience.policy import RetryPolicy
from triton_dist_tpu.resilience.watchdog import (
    CommTimeoutError, HealthTracker,
)
from triton_dist_tpu.serving import DisaggServingEngine, ServingEngine
from triton_dist_tpu.serving.server import (
    load_checkpoint, save_checkpoint,
)

CFG = ModelConfig.tiny()
TINY = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                        intermediate_size=32, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=4,
                        head_dim=8)
MAX_LEN = 64
PAGE = 8
BUCKETS = (4, 16)


@pytest.fixture(scope="module")
def role_engines():
    params = dense.init_params(jax.random.PRNGKey(3), CFG)
    devs = jax.devices()
    pf = Engine(CFG, Mesh(np.array(devs[:2]), ("tp",)), mode="xla",
                max_len=MAX_LEN, params=params)
    dec = Engine(CFG, Mesh(np.array(devs[2:4]), ("tp",)), mode="xla",
                 max_len=MAX_LEN, params=params)
    return pf, dec


@pytest.fixture(scope="module")
def tiny_engine():
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    return Engine(TINY, mesh, mode="xla", max_len=96, seed=0)


def _baseline(engine, prompt, gen_len):
    n = engine.mesh.shape[engine.axis]
    ids = jnp.asarray(np.tile(np.asarray([prompt], np.int32), (n, 1)))
    return np.asarray(engine.serve(ids, gen_len=gen_len))[0].tolist()


def _disagg(role_engines, **kw):
    pf, dec = role_engines
    kw.setdefault("num_slots", 2)
    kw.setdefault("page", PAGE)
    kw.setdefault("prefill_buckets", BUCKETS)
    return DisaggServingEngine(dec, prefill_engine=pf, **kw)


# ---------------------------------------------------------------------------
# RetryPolicy units (pure host logic)
# ---------------------------------------------------------------------------

def test_retry_policy_deterministic_schedule():
    pol = RetryPolicy(max_attempts=4, base_delay_s=0.5, multiplier=2.0,
                      max_delay_s=1.5, jitter=0.5, seed=9)
    assert pol.delays() == pol.delays(), "seeded jitter must replay"
    assert len(pol.delays()) == 3
    nj = RetryPolicy(max_attempts=4, base_delay_s=0.5, multiplier=2.0,
                     max_delay_s=1.5)
    assert nj.delays() == (0.5, 1.0, 1.5)   # capped at max_delay_s
    for got, base in zip(pol.delays(), nj.delays()):
        assert base <= got <= base * 1.5    # jitter in [0, 50%]


def test_retry_policy_absorbs_then_exhausts():
    calls = []

    def flaky(fail_n):
        def fn():
            calls.append(1)
            if len(calls) <= fail_n:
                raise TimeoutError("transient")
            return "ok"
        return fn

    pol = RetryPolicy(max_attempts=3)
    out, n = pol.call(flaky(2), retry_on=(TimeoutError,),
                      sleep=lambda d: None)
    assert (out, n) == ("ok", 3)
    calls.clear()
    with pytest.raises(TimeoutError):
        pol.call(flaky(99), retry_on=(TimeoutError,),
                 sleep=lambda d: None)
    assert len(calls) == 3, "max_attempts bounds total tries"


def test_retry_policy_non_retryable_propagates():
    pol = RetryPolicy(max_attempts=5)
    calls = []

    def fn():
        calls.append(1)
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        pol.call(fn, retry_on=(TimeoutError,), sleep=lambda d: None)
    assert len(calls) == 1, "a non-transient must not be retried"


def test_retry_policy_deadline_bounds_wall_clock():
    pol = RetryPolicy(max_attempts=100, base_delay_s=10.0)
    calls = []

    def fn():
        calls.append(1)
        raise TimeoutError("down")

    with pytest.raises(TimeoutError):
        pol.call(fn, retry_on=(TimeoutError,), deadline_s=1.0,
                 sleep=lambda d: None)
    assert len(calls) == 1, ("the next 10s backoff would exceed the "
                             "1s deadline — stop immediately")


def test_retry_policy_validation():
    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        RetryPolicy(jitter=1.5)
    # engine-side validation of the retry knob
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    eng = Engine(TINY, mesh, mode="xla", max_len=32, seed=0)
    with pytest.raises(TypeError):
        ServingEngine(eng, num_slots=2, page=8, retry="3 times")
    with pytest.raises(TypeError):
        ServingEngine(eng, num_slots=2, page=8,
                      retry={"page_migration": 3})


def test_health_tracker_thresholds():
    t = [0.0]
    ht = HealthTracker(fail_threshold=2, dead_after_s=5.0,
                       clock=lambda: t[0])
    assert not ht.fail("a")
    ht.beat()                      # progress resets the streak
    assert not ht.fail("b")
    assert ht.fail("c"), "2 consecutive failures cross the threshold"
    assert ht.dead and not ht.fail("d"), "death fires exactly once"
    ht2 = HealthTracker(fail_threshold=3, dead_after_s=5.0,
                        clock=lambda: t[0])
    t[0] = 6.0
    assert ht2.stalled()
    assert ht2.declare_dead("stall") and not ht2.declare_dead("again")


# ---------------------------------------------------------------------------
# Migration/chunk retry through the serving loop
# ---------------------------------------------------------------------------

def test_transient_migration_retried_token_exact(role_engines):
    pf, dec = role_engines
    srv = _disagg(role_engines, retry=RetryPolicy(max_attempts=3))
    h = srv.submit([1, 2, 3, 4, 5], max_new_tokens=4)
    with faults.inject(faults.get_plan("fail_kth_call",
                                       op="page_migration", k=0)):
        srv.run()
    assert h.status == "done", (h.status, h.error)
    assert h.tokens == _baseline(dec, [1, 2, 3, 4, 5], 4)
    st = srv.stats()
    assert st["retries"] >= 1 and st["failovers"] == 0
    chaos.check_invariants(srv)


def test_transient_wedged_chunk_retried(role_engines):
    pf, dec = role_engines
    srv = _disagg(role_engines, retry=RetryPolicy(max_attempts=2))
    h = srv.submit(list(range(1, 10)), max_new_tokens=3)
    with faults.inject(faults.get_plan("wedge_kth_call",
                                       op="chunked_prefill", k=0)):
        srv.run()
    assert h.status == "done" and h.tokens == _baseline(
        dec, list(range(1, 10)), 3)
    st = srv.stats()
    assert st["retries"] >= 1
    assert st["comm_timeouts"] >= 1, ("a timeout_call wedge surfaces "
                                      "as a CommTimeoutError")
    chaos.check_invariants(srv)


def test_no_retry_configured_keeps_fail_one(role_engines):
    """Without a policy the pre-existing containment is untouched:
    one dropped migration fails one request, zero retries."""
    srv = _disagg(role_engines, failover=False)
    h = srv.submit([7, 7, 7], max_new_tokens=3)
    with faults.inject(faults.FaultPlan(
            name="hard", faults=(faults.Fault(
                "fail_call", op="page_migration", k=None),))):
        for _ in range(20):
            if h.done:
                break
            srv.step()
    assert h.status == "failed" and srv.stats()["retries"] == 0
    # the server survives: a fresh request serves normally
    ok = srv.submit([5, 5], max_new_tokens=3)
    srv.run()
    assert ok.status == "done"
    chaos.check_invariants(srv)


def test_retry_exhausted_retires_with_zero_leaked_pages(role_engines):
    """The _retire audit: 3 consecutive failed migrations (retries
    exhausted each time) must release decode pages, staging pages AND
    the prefill-worker slot — both pools fully free afterwards."""
    srv = _disagg(role_engines, retry=RetryPolicy(max_attempts=2),
                  failover=False, prefix_reuse=False)
    hs = [srv.submit([i + 1, i + 2, i + 3], max_new_tokens=3)
          for i in range(3)]
    with faults.inject(faults.FaultPlan(
            name="hard", faults=(faults.Fault(
                "fail_call", op="page_migration", k=None),))):
        for _ in range(60):
            if all(h.done for h in hs):
                break
            srv.step()
    assert [h.status for h in hs] == ["failed"] * 3
    st = srv.stats()
    assert st["pool"]["free_pages"] == st["pool"]["num_pages"] - 1, (
        f"decode pages leaked: {st['pool']}")
    assert (st["prefill_pool"]["free_pages"]
            == st["prefill_pool"]["num_pages"] - 1), (
        f"staging pages leaked: {st['prefill_pool']}")
    assert st["retries"] == 3, "one retry per request before giving up"
    assert not srv.sched.slots, "prefill-worker slots all recycled"
    chaos.check_invariants(srv)


# ---------------------------------------------------------------------------
# Prefill-worker failover
# ---------------------------------------------------------------------------

def test_hard_faults_declare_worker_dead_and_fail_over(role_engines):
    pf, dec = role_engines
    srv = _disagg(role_engines, retry=RetryPolicy(max_attempts=2),
                  worker_fail_threshold=1)
    h = srv.submit([9, 8, 7, 6, 5, 4], max_new_tokens=4)
    with faults.inject(faults.FaultPlan(
            name="hard", faults=(faults.Fault(
                "fail_call", op="page_migration", k=None),))):
        for _ in range(30):
            if srv._drained():
                break
            srv.step()
    srv.run()
    st = srv.stats()
    assert st["failovers"] == 1
    assert st["roles"] == "prefill+decode/failover-local"
    assert srv.prefill_worker is None and srv.migration == "local"
    # The request the final failure hit was REQUEUED, not failed, and
    # finished token-exact on the local path.
    assert h.status == "done"
    assert h.tokens == _baseline(dec, [9, 8, 7, 6, 5, 4], 4)
    chaos.check_invariants(srv)


def test_operator_kill_mid_stream_token_exact(role_engines):
    pf, dec = role_engines
    srv = _disagg(role_engines)
    long_p = list(range(1, 12))
    h1 = srv.submit(long_p, max_new_tokens=5)
    h2 = srv.submit([5, 5], max_new_tokens=5)
    srv.step()
    srv.step()      # h1 mid-chunk-stream / mid-migration
    assert srv.fail_prefill_worker()
    assert not srv.fail_prefill_worker(), "second kill is a no-op"
    srv.run()
    assert h1.tokens == _baseline(dec, long_p, 5)
    assert h2.tokens == _baseline(dec, [5, 5], 5)
    assert srv.stats()["failovers"] == 1
    assert srv.stats()["dead_prefill_workers"] == 1
    chaos.check_invariants(srv)


def test_failover_to_surviving_standby_worker():
    """N>1 prefill workers: killing the active one moves prefill to
    the standby (still a WORKER role, not the local path), then
    killing that one degrades to local."""
    params = dense.init_params(jax.random.PRNGKey(3), CFG)
    devs = jax.devices()
    pf_a = Engine(CFG, Mesh(np.array(devs[:2]), ("tp",)), mode="xla",
                  max_len=MAX_LEN, params=params)
    pf_b = Engine(CFG, Mesh(np.array(devs[4:6]), ("tp",)), mode="xla",
                  max_len=MAX_LEN, params=params)
    dec = Engine(CFG, Mesh(np.array(devs[2:4]), ("tp",)), mode="xla",
                 max_len=MAX_LEN, params=params)
    srv = DisaggServingEngine(dec, prefill_engines=[pf_a, pf_b],
                              num_slots=2, page=PAGE,
                              prefill_buckets=BUCKETS)
    assert srv.stats()["prefill_workers"] == 2
    h1 = srv.submit([1, 2, 3, 4, 5, 6, 7], max_new_tokens=4)
    srv.step()
    assert srv.fail_prefill_worker()
    assert srv.prefill_worker is srv.prefill_workers[1], (
        "standby worker takes over")
    srv.run()
    assert h1.tokens == _baseline(dec, [1, 2, 3, 4, 5, 6, 7], 4)
    h2 = srv.submit([9, 9, 2], max_new_tokens=4)
    assert srv.fail_prefill_worker()
    srv.run()
    assert srv.prefill_worker is None, "no survivors -> local path"
    assert h2.tokens == _baseline(dec, [9, 9, 2], 4)
    assert srv.stats()["failovers"] == 2
    assert srv.stats()["dead_prefill_workers"] == 2
    chaos.check_invariants(srv)


def test_prefill_engine_and_engines_mutually_exclusive(role_engines):
    pf, dec = role_engines
    with pytest.raises(ValueError):
        DisaggServingEngine(dec, prefill_engine=pf,
                            prefill_engines=[pf], num_slots=2,
                            page=PAGE, prefill_buckets=BUCKETS)


# ---------------------------------------------------------------------------
# Checkpoint / restore
# ---------------------------------------------------------------------------

def test_checkpoint_restore_mid_run_token_exact(tiny_engine):
    """The kill/restore drill: snapshot mid-decode, rebuild a fresh
    engine, restore, finish — every request token-exact vs the
    uninterrupted run."""
    eng = tiny_engine
    prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]
    want = [_baseline(eng, p, 6) for p in prompts]
    srv = ServingEngine(eng, num_slots=2, page=8)
    hs = [srv.submit(p, max_new_tokens=6) for p in prompts]
    for _ in range(3):
        srv.step()      # two running mid-stream, one still queued
    snap = srv.checkpoint()
    fresh = ServingEngine(eng, num_slots=2, page=8)
    revived = fresh.restore(snap)
    assert len(revived) == 3
    assert fresh.stats()["restored_requests"] == 3
    fresh.run()
    got = {h.request.request_id: h.tokens for h in revived}
    for h, w in zip(hs, want):
        assert got[h.request.request_id] == w
    chaos.check_invariants(fresh)


def test_checkpoint_is_side_effect_free(tiny_engine):
    """checkpoint() observes; the live engine must finish exactly as
    if it had never been called."""
    eng = tiny_engine
    srv = ServingEngine(eng, num_slots=2, page=8)
    h = srv.submit([3, 1, 4, 1], max_new_tokens=6)
    srv.step()
    before = srv.manager.snapshot()
    srv.checkpoint()
    assert srv.manager.snapshot() == before
    srv.run()
    assert h.tokens == _baseline(eng, [3, 1, 4, 1], 6)


def test_restore_prefix_shared_pages_and_refcounts(tiny_engine):
    """Prefix-shared pages restore with their LIVE refcounts: two
    sharers + the cache ref survive the round-trip, and a post-restore
    third sharer still hits the warm prefix cache."""
    eng = tiny_engine
    pre = list(range(1, 9))                    # one full shared page
    srv = ServingEngine(eng, num_slots=2, page=8, prefix_reuse=True)
    h1 = srv.submit(pre + [20, 21], max_new_tokens=6)
    h2 = srv.submit(pre + [30], max_new_tokens=6)
    for _ in range(3):
        srv.step()
    assert srv.manager.prefix_hits(h2.slot) == 1
    snap = srv.checkpoint()
    fresh = ServingEngine(eng, num_slots=2, page=8, prefix_reuse=True)
    revived = fresh.restore(snap)
    assert fresh.manager._refs == srv.manager._refs
    assert fresh.manager._prefix == srv.manager._prefix
    fresh.run()
    got = {h.request.request_id: h.tokens for h in revived}
    ref = ServingEngine(eng, num_slots=2, page=8, prefix_reuse=True)
    want = ref.generate([pre + [20, 21], pre + [30]], max_new_tokens=6)
    assert [got[h1.request.request_id],
            got[h2.request.request_id]] == want
    # warm cache: a new same-prefix request hits without recompute
    hits0 = fresh.manager.stats["prefix_hits"]
    h3 = fresh.submit(pre + [40], max_new_tokens=2)
    fresh.run()
    assert fresh.manager.stats["prefix_hits"] > hits0
    assert h3.status == "done"
    chaos.check_invariants(fresh)


@pytest.mark.parametrize("kvd", ["int8", "fp8"])
def test_restore_quantized_pool_scales_bit_exact(tiny_engine, kvd):
    eng = tiny_engine
    srv = ServingEngine(eng, num_slots=2, page=8, kv_dtype=kvd)
    hs = [srv.submit([1, 2, 3, 4, 5], max_new_tokens=6),
          srv.submit([9, 8], max_new_tokens=6)]
    for _ in range(2):
        srv.step()
    snap = srv.checkpoint()
    # cross-process fidelity: the snapshot must survive pickling
    # (ml_dtypes fp8 pools included)
    import pickle

    snap = pickle.loads(pickle.dumps(snap))
    fresh = ServingEngine(eng, num_slots=2, page=8, kv_dtype=kvd)
    revived = fresh.restore(snap)
    np.testing.assert_array_equal(np.asarray(fresh.cache.k_scale),
                                  np.asarray(srv.cache.k_scale))
    np.testing.assert_array_equal(np.asarray(fresh.cache.v_scale),
                                  np.asarray(srv.cache.v_scale))
    np.testing.assert_array_equal(
        np.asarray(fresh.cache.k_pages).view(np.uint8),
        np.asarray(srv.cache.k_pages).view(np.uint8))
    fresh.run()
    ref = ServingEngine(eng, num_slots=2, page=8, kv_dtype=kvd)
    want = ref.generate([[1, 2, 3, 4, 5], [9, 8]], max_new_tokens=6)
    got = {h.request.request_id: h.tokens for h in revived}
    assert [got[h.request.request_id] for h in hs] == want
    chaos.check_invariants(fresh)


def test_restore_mid_speculative_draft(tiny_engine):
    """Checkpoint with spec_k active (rollback mirrors mid-flight):
    the restored engine's spec loop continues token-exact vs the
    non-spec greedy oracle."""
    eng = tiny_engine
    prompt = [1, 2, 3, 1, 2, 3, 1, 2]
    srv = ServingEngine(eng, num_slots=2, page=8, spec_k=3)
    h = srv.submit(prompt, max_new_tokens=10)
    for _ in range(2):
        srv.step()
    snap = srv.checkpoint()
    fresh = ServingEngine(eng, num_slots=2, page=8, spec_k=3)
    revived = fresh.restore(snap)
    fresh.run()
    assert revived[0].tokens == _baseline(eng, prompt, 10)
    assert fresh.decode_cache_size() == 1
    chaos.check_invariants(fresh)


def test_restore_rejects_mismatched_plan(tiny_engine):
    eng = tiny_engine
    srv = ServingEngine(eng, num_slots=2, page=8)
    srv.submit([1, 2], max_new_tokens=2)
    srv.step()
    snap = srv.checkpoint()
    with pytest.raises(ValueError, match="mismatch"):
        ServingEngine(eng, num_slots=4, page=8).restore(snap)
    with pytest.raises(ValueError, match="mismatch"):
        ServingEngine(eng, num_slots=2, page=8,
                      kv_dtype="int8").restore(snap)
    with pytest.raises(ValueError, match="not a serving checkpoint"):
        ServingEngine(eng, num_slots=2, page=8).restore({"meta": {}})
    busy = ServingEngine(eng, num_slots=2, page=8)
    busy.submit([1], max_new_tokens=2)
    with pytest.raises(RuntimeError, match="idle"):
        busy.restore(snap)
    srv.run()


def test_checkpoint_file_roundtrip_atomic(tiny_engine, tmp_path):
    eng = tiny_engine
    srv = ServingEngine(eng, num_slots=2, page=8)
    srv.submit([1, 2, 3], max_new_tokens=4)
    srv.step()
    path = str(tmp_path / "serving.ckpt")
    save_checkpoint(srv.checkpoint(), path)
    snap = load_checkpoint(path)
    fresh = ServingEngine(eng, num_slots=2, page=8)
    revived = fresh.restore(snap)
    fresh.run()
    assert revived[0].tokens == _baseline(eng, [1, 2, 3], 4)
    leftovers = [p for p in tmp_path.iterdir() if "tmp" in p.name]
    assert not leftovers, "atomic save must not strand temp files"
    srv.run()


def test_disagg_checkpoint_requeues_inflight(role_engines):
    """Disaggregated checkpoint: mid-prefill / mid-migration work
    snapshots as QUEUED (partial staging dropped), restores into a
    fresh two-role engine, finishes token-exact."""
    pf, dec = role_engines
    srv = _disagg(role_engines, prefix_reuse=True)
    long_p = list(range(1, 12))
    h1 = srv.submit(long_p, max_new_tokens=4)
    h2 = srv.submit([5, 5], max_new_tokens=4)
    srv.step()          # h1 mid-chunk-stream
    snap = srv.checkpoint()
    fresh = _disagg(role_engines, prefix_reuse=True)
    revived = fresh.restore(snap)
    fresh.run()
    got = {h.request.request_id: h.tokens for h in revived}
    assert got[h1.request.request_id] == _baseline(dec, long_p, 4)
    assert got[h2.request.request_id] == _baseline(dec, [5, 5], 4)
    chaos.check_invariants(fresh)
    srv2_stats = fresh.stats()
    assert srv2_stats["restored_requests"] == 2


# One megakernel engine per kv_dtype for the module: restore()
# overwrites pools/scales wholesale, so even the "fresh process"
# half of the round-trip can share the engine (what a real fresh
# process repacks — the weights — is identical by construction).
_MK_ENGINES: dict = {}


def _mk_serving(kv_dtype="bf16"):
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    if kv_dtype not in _MK_ENGINES:
        cfg = ModelConfig.tiny(vocab_size=128)
        mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
        _MK_ENGINES[kv_dtype] = MegaKernelEngine(
            cfg, mesh, batch=2, max_len=32, tile_w=16, t_tile=16,
            paged=True, page=16, num_pages=5, kv_dtype=kv_dtype)
    return ServingEngine(_MK_ENGINES[kv_dtype], kv_dtype=kv_dtype)


@pytest.mark.parametrize("kvd", ["bf16", "int8"])
def test_megakernel_checkpoint_restore_token_exact(kvd):
    """The converted mk-reject: a schema-driven checkpoint (KV pools +
    scale tables + counters by arena-region name) restores into a
    FRESH megakernel engine and resumes mid-stream decode token-exact
    — bit-exact pools at bf16 AND int8. A mid-prefill-LANE request
    snapshots as queued and re-prefills deterministically."""
    prompts = [[5, 6, 7], [3, 4]]
    want = _mk_serving(kvd).generate(prompts, max_new_tokens=6)
    srv = _mk_serving(kvd)
    h0 = srv.submit(prompts[0], max_new_tokens=6)
    for _ in range(6):       # h0 mid-decode
        srv.step()
    h1 = srv.submit(prompts[1], max_new_tokens=6)
    srv.step()               # h1 mid-prefill-lane
    assert h0.status == "running" and h0.tokens
    snap = srv.checkpoint()
    fresh = _mk_serving(kvd)
    revived = {h.request.request_id: h for h in fresh.restore(snap)}
    fresh.run()
    got = [revived[h0.request.request_id].tokens,
           revived[h1.request.request_id].tokens]
    assert got == want, (kvd, got, want)
    assert fresh.stats()["restored_requests"] == 2
    assert fresh.stats()["mk_checkpointable"] is True
    chaos.check_invariants(fresh)


def test_megakernel_checkpoint_file_roundtrip(tmp_path):
    """The pickle path carries the mk snapshot too (int8 pool bytes
    view-round-trip through numpy, scale planes exact)."""
    from triton_dist_tpu.serving.server import (load_checkpoint,
                                                save_checkpoint)

    srv = _mk_serving("int8")
    srv.submit([5, 6, 7], max_new_tokens=6)
    for _ in range(5):
        srv.step()
    snap = srv.checkpoint()
    p = save_checkpoint(snap, str(tmp_path / "mk.ckpt"))
    snap2 = load_checkpoint(p)
    np.testing.assert_array_equal(
        snap["cache"]["k_cache"].view(np.uint8),
        snap2["cache"]["k_cache"].view(np.uint8))
    np.testing.assert_array_equal(snap["cache"]["k_scale"],
                                  snap2["cache"]["k_scale"])
    fresh = _mk_serving("int8")
    revived = fresh.restore(snap2)
    fresh.run()
    assert all(h.status == "done" for h in revived)


def test_megakernel_checkpoint_meta_mismatch_rejected():
    """A layer-path snapshot cannot restore into an mk engine (and
    vice versa): the engine_kind meta key fails the plan check."""
    srv = _mk_serving()
    snap = srv.checkpoint()
    snap["meta"]["engine_kind"] = "layer"
    fresh = _mk_serving()
    with pytest.raises(ValueError, match="plan mismatch"):
        fresh.restore(snap)


# ---------------------------------------------------------------------------
# migrate_pages_host's own retry knob (ops/p2p.py surface)
# ---------------------------------------------------------------------------

def test_migrate_pages_host_retry_param():
    """The op-level retry knob: same bit-exact payload through the
    bridge put whether or not a policy wraps it."""
    from triton_dist_tpu.ops.p2p import migrate_pages_host

    devs = jax.devices()
    bridge = Mesh(np.array(devs[:2]), ("role",))
    k = np.arange(2 * 3 * 2 * 4 * 2, dtype=np.float32).reshape(
        2, 3, 2, 4, 2)
    v = k + 100.0
    kk, vv = migrate_pages_host(k, v, bridge, axis="role", src=0,
                                dst=1, retry=RetryPolicy(max_attempts=2))
    np.testing.assert_array_equal(kk, k)
    np.testing.assert_array_equal(vv, v)
