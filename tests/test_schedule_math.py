"""Host-side schedule math at WIDE K.

The interpret harness starves above ~64 KB per staged buffer, so the
K=4096 regime — where the panel policy's (tm, K) footprint forces tm
halving while the streamed policy's footprint stays K-independent —
can never run as a device test. The staging decisions live in pure
host functions (``ops/ag_gemm.panel_blocks`` / ``pipelined_blocks``,
``lang/overlap.stream_plan`` / ``choose_depth``), so the wide-K
behaviour is unit-tested here with no device buffers at all.
"""

import importlib

import pytest

from triton_dist_tpu.lang import overlap
from triton_dist_tpu.tools import perf_model

ag = importlib.import_module("triton_dist_tpu.ops.ag_gemm")

BF16 = 2
F32 = 4
K_WIDE = 4096


# ---------------------------------------------------------------------------
# 1. tile policies at K=4096: the panel/streamed footprint divergence
# ---------------------------------------------------------------------------

def test_panel_tm_halves_under_wide_k_budget():
    """(tm, K) panel at tm=2048, K=4096, bf16 is 16 MB > the 9 MB
    budget -> tm halves once to 1024 (8 MB fits)."""
    tm, tn, tk, n_i, n_j, n_k, n_buf = ag.panel_blocks(
        2048, 256, 512, m_loc=2048, n_loc=256, kdim=K_WIDE,
        itemsize=BF16, n_ranks=8)
    assert tm == 1024
    assert (n_i, n_j, n_k) == (2, 1, 8)
    # Even the halved panel cannot double-buffer: 2 x 8 MB > 9 MB.
    assert n_buf == 1


def test_pipelined_tm_survives_wide_k():
    """Same shape, streamed policy: the (tm, tk) pair footprint does
    not grow with K, so tm stays at the full 2048 AND the stream
    double-buffers — the fine-granularity win the panel variant
    structurally cannot reach at wide K."""
    tm, tn, tk, n_i, n_j, n_k, n_buf = ag.pipelined_blocks(
        2048, 256, 512, m_loc=2048, n_loc=256, kdim=K_WIDE,
        itemsize=BF16, n_ranks=8)
    assert (tm, tn, tk) == (2048, 256, 512)
    assert (n_i, n_j, n_k) == (1, 1, 8)
    assert n_buf == 2


def test_pipelined_tk_budget_halving():
    """tk halves until a double-buffered (tm,tk)+(tk,tn) pair fits the
    budget: 2*(8+8)*4096*4 B = 512 KB > 128 KB -> 4096 -> 2048 -> 1024
    (2*(8+8)*1024*4 = 128 KB fits)."""
    tm, tn, tk, _, _, n_k, n_buf = ag.pipelined_blocks(
        8, 8, K_WIDE, m_loc=8, n_loc=8, kdim=K_WIDE, itemsize=F32,
        n_ranks=4, budget=128 * 1024)
    assert (tm, tn) == (8, 8)
    assert tk == 1024 and n_k == 4
    assert n_buf == 2


def test_pipelined_tk_floors_at_8():
    """The budget clamp never shrinks tk below the lane width: an
    impossible budget floors tk at 8 rather than degenerating."""
    *_, tk, _, _, n_k, n_buf = ag.pipelined_blocks(
        8, 8, K_WIDE, m_loc=8, n_loc=8, kdim=K_WIDE, itemsize=F32,
        n_ranks=4, budget=1)
    assert tk == 8 and n_k == K_WIDE // 8
    assert n_buf == 1  # nothing double-buffers under a 1-byte budget


@pytest.mark.parametrize("policy", ["panel", "pipelined"])
def test_ragged_m_snaps_to_divisor(policy):
    """m_loc=192 with block_m=128: 128 does not divide 192, so tm
    snaps down through the halving chain to 64 in both policies."""
    fn = ag.panel_blocks if policy == "panel" else ag.pipelined_blocks
    tm, _, _, n_i, _, _, _ = fn(128, 8, 512, m_loc=192, n_loc=8,
                                kdim=K_WIDE, itemsize=BF16, n_ranks=8)
    assert tm == 64 and n_i == 3


@pytest.mark.parametrize("policy", ["panel", "pipelined"])
def test_non_divisible_tn_raises(policy):
    """tn has no snapping chain — a non-divisor block_n is a config
    error, surfaced eagerly on the host."""
    fn = ag.panel_blocks if policy == "panel" else ag.pipelined_blocks
    with pytest.raises(ValueError, match="must\n?.*divide"):
        fn(8, 8, 512, m_loc=16, n_loc=100, kdim=K_WIDE,
           itemsize=BF16, n_ranks=8)


def test_pipelined_non_divisible_tk_raises():
    """A prime K that the halving chain cannot reach raises rather
    than silently mis-tiling (tk floors at 8 without dividing 4097)."""
    with pytest.raises(ValueError, match="divide"):
        ag.pipelined_blocks(8, 8, 512, m_loc=16, n_loc=8, kdim=4097,
                            itemsize=BF16, n_ranks=8)


def test_vmem_model_matches_pipelined_policy():
    """The autotuner prunes on ``perf_model.ag_gemm_pipelined_vmem_bytes``
    — it must equal the footprint the policy actually allocates
    (n_buf pairs + f32 acc + double-buffered out) at every wide-K
    corner, or pruning diverges from reality."""
    shapes = [
        (2048, 256, 512, 2048, 256, K_WIDE, BF16),
        (8, 8, K_WIDE, 8, 8, K_WIDE, F32),
        (128, 8, 512, 192, 8, K_WIDE, BF16),
        (256, 128, 256, 256, 128, 1024, BF16),
    ]
    for bm, bn, bk, m_loc, n_loc, kdim, isz in shapes:
        tm, tn, tk, _, _, _, n_buf = ag.pipelined_blocks(
            bm, bn, bk, m_loc=m_loc, n_loc=n_loc, kdim=kdim,
            itemsize=isz, n_ranks=8)
        want = (n_buf * (tm * tk + tk * tn) * isz
                + tm * tn * 4 + 2 * tm * tn * isz)
        got = perf_model.ag_gemm_pipelined_vmem_bytes(
            bm, bn, bk, m_loc, kdim, n_loc, dtype_bytes=isz)
        assert got == want, (bm, bn, bk, m_loc, n_loc, kdim, isz)


# ---------------------------------------------------------------------------
# 2. stream_plan: the host mirror of stream_scoped's DMA schedule
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("total,depth", [
    (8, 1), (8, 2), (8, 3), (1, 2), (2, 3), (0, 2),
    (K_WIDE // 512, 2),
])
def test_stream_plan_starts_each_panel_once(total, depth):
    lead, stages = overlap.stream_plan(total, depth)
    assert len(stages) == total
    started = list(lead) + [s for st in stages for s in st]
    assert sorted(started) == list(range(total))


@pytest.mark.parametrize("total,depth", [(8, 2), (8, 3), (16, 2)])
def test_stream_plan_buffer_safety(total, depth):
    """At step t the consumer reads buffer t % depth; any start issued
    at step t targets a panel whose buffer slot was last consumed at a
    STRICTLY earlier step — no in-flight DMA ever lands on the buffer
    being read."""
    lead, stages = overlap.stream_plan(total, depth)
    for p in lead:
        assert p < depth - 1          # lead loads fill slots 0..d-2
    for t, st in enumerate(stages):
        for p in st:
            assert p == t + depth - 1
            assert p % depth != t % depth


def test_stream_plan_depth1_is_stage_and_wait():
    lead, stages = overlap.stream_plan(5, 1)
    assert lead == ()
    assert stages == tuple((t,) for t in range(5))


def test_stream_plan_rejects_bad_args():
    with pytest.raises(ValueError):
        overlap.stream_plan(-1, 2)
    with pytest.raises(ValueError):
        overlap.stream_plan(4, 0)


# ---------------------------------------------------------------------------
# 3. choose_depth at the wide-K boundary
# ---------------------------------------------------------------------------

def test_choose_depth_wide_k_budget_walkdown():
    """An 8 MB wide-K panel cannot double-buffer in 9 MB: explicit
    depth 3 walks down to 1, never rejects."""
    panel = 1024 * K_WIDE * BF16
    assert overlap.choose_depth(3, panel, 9 * 1024 * 1024, None, 8) == 1


def test_choose_depth_chunk_len_none_skips_body_guard():
    """chunk_len=None (within-body staging) keeps depth 2 even where a
    single-body-per-chunk grid would force cross-chunk staging to 1."""
    pair = 64 * 1024
    budget = 9 * 1024 * 1024
    assert overlap.choose_depth(0, pair, budget, None, 8) == 2
    assert overlap.choose_depth(0, pair, budget, 1, 8) == 1


def test_choose_depth_clamps_to_panel_count():
    assert overlap.choose_depth(3, 1024, 9 * 1024 * 1024, None, 1) == 1
    assert overlap.choose_depth(3, 1024, 9 * 1024 * 1024, None, 2) == 2
