"""Sequence-parallel ops: Ulysses A2A resharding, ring KV-AG attention,
distributed split-KV flash decode — vs dense oracles (reference:
``test_sp_ag_attention_*``, ``test_ulysses_*``, ``test_flash_decode``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.ulysses import (
    pre_attn_a2a, post_attn_a2a, ulysses_attn,
)
from triton_dist_tpu.ops.sp_ag_attention import (
    sp_ag_attention, sp_ag_attention_ref,
)
from triton_dist_tpu.ops.flash_decode import (
    sp_flash_decode, flash_decode_ref,
)
from triton_dist_tpu.layers.tp_attn import sdpa
from triton_dist_tpu.utils.testing import spmd, assert_allclose


def _rand(shape, seed, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_ulysses_pre_post_roundtrip(tp8_mesh, tp8_ctx, impl):
    s, h, hd = 64, 8, 16
    x = _rand((s, h, hd), 0)

    def run(v):
        y = pre_attn_a2a(v, axis="tp", ctx=tp8_ctx, impl=impl)
        return post_attn_a2a(y, axis="tp", ctx=tp8_ctx, impl=impl)

    f = spmd(tp8_mesh, run, P("tp", None, None), P("tp", None, None))
    assert_allclose(f(x), x)


def test_ulysses_attention_vs_dense(tp8_mesh, tp8_ctx):
    s, h, hd = 64, 8, 16
    q = _rand((s, h, hd), 1)
    k = _rand((s, h, hd), 2)
    v = _rand((s, h, hd), 3)

    f = spmd(tp8_mesh,
             lambda a, b, c: ulysses_attn(a, b, c, axis="tp", ctx=tp8_ctx),
             (P("tp", None, None),) * 3, P("tp", None, None))
    out = f(q, k, v)
    expected = sdpa(q[None], k[None], v[None], causal=True)[0]
    assert_allclose(out, expected, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_sp_ag_attention_vs_ref(tp8_mesh, tp8_ctx, causal):
    s, h, hd = 64, 4, 16
    q = _rand((s, h, hd), 4)
    k = _rand((s, h, hd), 5)
    v = _rand((s, h, hd), 6)

    f = spmd(tp8_mesh,
             lambda a, b, c: sp_ag_attention(a, b, c, axis="tp",
                                             causal=causal),
             (P("tp", None, None),) * 3, P("tp", None, None))
    g = spmd(tp8_mesh,
             lambda a, b, c: sp_ag_attention_ref(a, b, c, axis="tp",
                                                 causal=causal),
             (P("tp", None, None),) * 3, P("tp", None, None))
    assert_allclose(f(q, k, v), g(q, k, v), rtol=1e-4, atol=1e-4)


def test_sp_ag_attention_gqa(tp8_mesh, tp8_ctx):
    s, h, kvh, hd = 64, 8, 4, 16
    q = _rand((s, h, hd), 7)
    k = _rand((s, kvh, hd), 8)
    v = _rand((s, kvh, hd), 9)
    f = spmd(tp8_mesh,
             lambda a, b, c: sp_ag_attention(a, b, c, axis="tp"),
             (P("tp", None, None),) * 3, P("tp", None, None))
    g = spmd(tp8_mesh,
             lambda a, b, c: sp_ag_attention_ref(a, b, c, axis="tp"),
             (P("tp", None, None),) * 3, P("tp", None, None))
    assert_allclose(f(q, k, v), g(q, k, v), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_sp_ag_attention_fused_vs_ref(tp8_mesh, tp8_ctx, causal):
    from triton_dist_tpu.ops import sp_ag_attention_fused

    s, h, hd = 64, 4, 16
    q = _rand((s, h, hd), 14)
    k = _rand((s, h, hd), 15)
    v = _rand((s, h, hd), 16)

    f = spmd(tp8_mesh,
             lambda a, b, c: sp_ag_attention_fused(
                 a, b, c, ctx=tp8_ctx, axis="tp", causal=causal,
                 block_q=4, block_kv=8),
             (P("tp", None, None),) * 3, P("tp", None, None))
    g = spmd(tp8_mesh,
             lambda a, b, c: sp_ag_attention_ref(a, b, c, axis="tp",
                                                 causal=causal),
             (P("tp", None, None),) * 3, P("tp", None, None))
    assert_allclose(f(q, k, v), g(q, k, v), rtol=1e-4, atol=1e-4)


def test_sp_ag_attention_fused_gqa(tp8_mesh, tp8_ctx):
    from triton_dist_tpu.ops import sp_ag_attention_fused

    s, h, kvh, hd = 64, 8, 4, 16
    q = _rand((s, h, hd), 17)
    k = _rand((s, kvh, hd), 18)
    v = _rand((s, kvh, hd), 19)
    f = spmd(tp8_mesh,
             lambda a, b, c: sp_ag_attention_fused(
                 a, b, c, ctx=tp8_ctx, axis="tp", block_q=8, block_kv=8),
             (P("tp", None, None),) * 3, P("tp", None, None))
    g = spmd(tp8_mesh,
             lambda a, b, c: sp_ag_attention_ref(a, b, c, axis="tp"),
             (P("tp", None, None),) * 3, P("tp", None, None))
    assert_allclose(f(q, k, v), g(q, k, v), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("inner,outer", [("tp", "dp"), ("dp", "tp")])
def test_sp_ag_attention_2d_vs_ref(dp2tp4_mesh, dp2tp4_ctx, inner, outer,
                                   causal):
    """Hierarchical (mirror+relay) schedule == dense oracle, both axis
    assignments (O=2/I=4 and O=4/I=2)."""
    from triton_dist_tpu.ops import sp_ag_attention_2d
    from triton_dist_tpu.ops.sp_ag_attention import _masked_attn

    s, h, hd = 64, 4, 16
    q = _rand((s, h, hd), 24)
    k = _rand((s, h, hd), 25)
    v = _rand((s, h, hd), 26)
    s_loc = s // 8

    def oracle(qs, ks, vs):
        glob = (jax.lax.axis_index(outer) * jax.lax.axis_size(inner)
                + jax.lax.axis_index(inner))
        kf = jax.lax.all_gather(
            jax.lax.all_gather(ks, inner, axis=0, tiled=True),
            outer, axis=0, tiled=True)
        vf = jax.lax.all_gather(
            jax.lax.all_gather(vs, inner, axis=0, tiled=True),
            outer, axis=0, tiled=True)
        return _masked_attn(qs, kf, vf, glob * s_loc, causal=causal)

    shard = P((outer, inner), None, None)
    f = spmd(dp2tp4_mesh,
             lambda a, b, c: sp_ag_attention_2d(
                 a, b, c, ctx=dp2tp4_ctx, inner_axis=inner,
                 outer_axis=outer, causal=causal, block_q=4, block_kv=8),
             (shard,) * 3, shard)
    g = spmd(dp2tp4_mesh, oracle, (shard,) * 3, shard)
    assert_allclose(f(q, k, v), g(q, k, v), rtol=1e-4, atol=1e-4)


def _varlen_oracle(q_full, k_full, v_full, cu):
    """Ragged dense oracle, independent of the implementation's mask
    helpers: slice the packed batch at each boundary and run plain
    causal attention per sequence."""
    cu = np.asarray(cu)
    out = np.zeros(np.asarray(q_full).shape, np.float32)
    for b, e in zip(cu[:-1], cu[1:]):
        if e <= b:
            continue
        seg = sdpa(jnp.asarray(q_full)[None, b:e],
                   jnp.asarray(k_full)[None, b:e],
                   jnp.asarray(v_full)[None, b:e], causal=True)[0]
        out[b:e] = np.asarray(seg, np.float32)
    return out


CU_MIXED = jnp.array([0, 5, 19, 40, 51, 64], jnp.int32)       # mixed
CU_PADDED = jnp.array([0, 24, 64, 64, 64, 64, 64], jnp.int32)  # padded
CU_ONE = jnp.array([0, 64], jnp.int32)                         # degenerate


@pytest.mark.parametrize("cu", [CU_MIXED, CU_PADDED, CU_ONE],
                         ids=["mixed", "padded", "single"])
def test_sp_ag_attention_varlen_vs_oracle(tp8_mesh, tp8_ctx, cu):
    """XLA ring varlen == ragged dense oracle (reference
    sp_ag_attention_intra_node.py:113 cu_seqlens batches)."""
    s, h, hd = 64, 4, 16
    q = _rand((s, h, hd), 27)
    k = _rand((s, h, hd), 28)
    v = _rand((s, h, hd), 29)

    f = spmd(tp8_mesh,
             lambda a, b, c: sp_ag_attention(a, b, c, axis="tp",
                                             cu_seqlens=cu),
             (P("tp", None, None),) * 3, P("tp", None, None))
    out = f(q, k, v)
    expected = _varlen_oracle(q, k, v, cu)
    assert_allclose(out, expected, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("cu", [CU_MIXED, CU_PADDED, CU_ONE],
                         ids=["mixed", "padded", "single"])
def test_sp_ag_attention_fused_varlen_vs_oracle(tp8_mesh, tp8_ctx, cu):
    """Fused kernel varlen (per-sequence masks + span-pruned sends) ==
    ragged dense oracle. CU_MIXED places sequence boundaries both
    inside chunks and across them; CU_PADDED makes ranks 4..7 share no
    sequence with ranks 0..2, exercising the send pruning."""
    from triton_dist_tpu.ops import sp_ag_attention_fused

    s, h, hd = 64, 4, 16
    q = _rand((s, h, hd), 30)
    k = _rand((s, h, hd), 31)
    v = _rand((s, h, hd), 32)

    f = spmd(tp8_mesh,
             lambda a, b, c: sp_ag_attention_fused(
                 a, b, c, ctx=tp8_ctx, axis="tp", block_q=4, block_kv=8,
                 cu_seqlens=cu),
             (P("tp", None, None),) * 3, P("tp", None, None))
    out = f(q, k, v)
    expected = _varlen_oracle(q, k, v, cu)
    assert_allclose(out, expected, rtol=1e-4, atol=1e-4)


def test_sp_ag_attention_fused_varlen_gqa_multitile(tp8_mesh, tp8_ctx):
    """Varlen fused with GQA (rep=2) and multiple KV tiles per chunk
    (block_kv < S_loc) — exercises the rep-row repetition in qi and the
    kvt*tkv offset in sid_k that the base varlen tests never hit."""
    from triton_dist_tpu.ops import sp_ag_attention_fused

    s, h, kvh, hd = 64, 8, 4, 16
    q = _rand((s, h, hd), 36)
    k = _rand((s, kvh, hd), 37)
    v = _rand((s, kvh, hd), 38)
    cu = CU_MIXED

    f = spmd(tp8_mesh,
             lambda a, b, c: sp_ag_attention_fused(
                 a, b, c, ctx=tp8_ctx, axis="tp", block_q=4, block_kv=4,
                 cu_seqlens=cu),
             (P("tp", None, None),) * 3, P("tp", None, None))
    out = f(q, k, v)
    rep = h // kvh
    expected = _varlen_oracle(q, jnp.repeat(k, rep, axis=1),
                              jnp.repeat(v, rep, axis=1), cu)
    assert_allclose(out, expected, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("inner,outer", [("tp", "dp"), ("dp", "tp")])
@pytest.mark.parametrize("cu", [CU_MIXED, CU_PADDED, CU_ONE],
                         ids=["mixed", "padded", "single"])
def test_sp_ag_attention_2d_varlen_vs_oracle(dp2tp4_mesh, dp2tp4_ctx,
                                             inner, outer, cu):
    """Hierarchical schedule varlen == ragged oracle (VERDICT r3 #7:
    the span predicate is threaded through all three send tiers —
    mirror pushes, group-level mirror acceptance, per-peer relays).
    CU_MIXED crosses chunk AND group boundaries; CU_PADDED makes the
    upper ranks share no sequence with the lower ones, exercising the
    mirror-skip and relay pruning; both axis assignments run."""
    from triton_dist_tpu.ops import sp_ag_attention_2d

    s, h, hd = 64, 4, 16
    q = _rand((s, h, hd), 33)
    k = _rand((s, h, hd), 34)
    v = _rand((s, h, hd), 35)

    shard = P((outer, inner), None, None)
    f = spmd(dp2tp4_mesh,
             lambda a, b, c: sp_ag_attention_2d(
                 a, b, c, ctx=dp2tp4_ctx, inner_axis=inner,
                 outer_axis=outer, block_q=4, block_kv=8,
                 cu_seqlens=cu),
             (shard,) * 3, shard)
    out = f(q, k, v)
    expected = _varlen_oracle(q, k, v, cu)
    assert_allclose(out, expected, rtol=1e-4, atol=1e-4)


def test_sp_ag_attention_varlen_single_equals_causal(tp8_mesh, tp8_ctx):
    """Degenerate one-sequence cu must reproduce the plain causal path
    bit-for-bit (same code path modulo masks)."""
    s, h, hd = 64, 4, 16
    q = _rand((s, h, hd), 33)
    k = _rand((s, h, hd), 34)
    v = _rand((s, h, hd), 35)
    f_var = spmd(tp8_mesh,
                 lambda a, b, c: sp_ag_attention(a, b, c, axis="tp",
                                                 cu_seqlens=CU_ONE),
                 (P("tp", None, None),) * 3, P("tp", None, None))
    f_pl = spmd(tp8_mesh,
                lambda a, b, c: sp_ag_attention(a, b, c, axis="tp"),
                (P("tp", None, None),) * 3, P("tp", None, None))
    assert_allclose(f_var(q, k, v), f_pl(q, k, v), rtol=0, atol=0)


def test_sp_flash_decode_vs_dense(tp8_mesh, tp8_ctx):
    b, h, kvh, hd, t = 4, 8, 4, 16, 64
    q = _rand((b, h, hd), 10)
    k = _rand((b, t, kvh, hd), 11)
    v = _rand((b, t, kvh, hd), 12)
    kv_len = jnp.array([64, 40, 17, 1], jnp.int32)

    # Cache sequence-sharded along tp (T_loc = 8 per rank).
    f = spmd(tp8_mesh,
             lambda a, b_, c, l: sp_flash_decode(a, b_, c, l, axis="tp"),
             (P(None, None, None), P(None, "tp", None, None),
              P(None, "tp", None, None), P(None)),
             P(None, None, None))
    out = f(q, k, v, kv_len)
    expected = flash_decode_ref(q, k, v, kv_len)
    assert_allclose(out, expected, rtol=1e-4, atol=1e-4)


def test_sp_flash_decode_2d_multislice(dp2tp4_mesh, dp2tp4_ctx):
    """Multi-slice split-KV decode: the cache shards over BOTH mesh
    axes (outer-major) and the LSE combine rides (dp, tp) — the
    hierarchical long-context decode regime (reference scales split-KV
    1->32 GPUs across nodes; here ICI x DCN in one call)."""
    b, h, kvh, hd, t = 2, 8, 4, 16, 64
    q = _rand((b, h, hd), 13)
    k = _rand((b, t, kvh, hd), 14)
    v = _rand((b, t, kvh, hd), 15)
    kv_len = jnp.array([60, 23], jnp.int32)

    f = spmd(dp2tp4_mesh,
             lambda a, b_, c, l: sp_flash_decode(
                 a, b_, c, l, axis=("dp", "tp")),
             (P(None, None, None), P(None, ("dp", "tp"), None, None),
              P(None, ("dp", "tp"), None, None), P(None)),
             P(None, None, None))
    out = f(q, k, v, kv_len)
    expected = flash_decode_ref(q, k, v, kv_len)
    assert_allclose(out, expected, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("gqa", [False, True], ids=["mha", "gqa"])
def test_sp_ag_attention_fused_sim_ranks(gqa):
    """Single-chip self-sim ring (the bench proxy): playing the LAST of
    sim_ranks ranks — all chunk arrivals via self-puts of true data —
    must equal dense causal attention of the last query slice over the
    full KV."""
    from jax.sharding import Mesh
    from triton_dist_tpu.ops import sp_ag_attention_fused
    from triton_dist_tpu.ops.sp_ag_attention import _masked_attn
    from triton_dist_tpu.parallel.mesh import MeshContext

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("tp",))
    ctx1 = MeshContext.from_mesh(mesh1)
    s, h, hd = 64, 4, 16
    kvh = 2 if gqa else h
    q = _rand((s, h, hd), 60) * 0.5
    k = _rand((s, kvh, hd), 61) * 0.5
    v = _rand((s, kvh, hd), 62) * 0.5
    n_sim = 4
    out = spmd(mesh1,
               lambda a, b, c: sp_ag_attention_fused(
                   a, b, c, ctx=ctx1, axis="tp", block_q=4, block_kv=8,
                   sim_ranks=n_sim),
               (P(None, None, None),) * 3, P(None, None, None))(q, k, v)
    s_loc = s // n_sim
    want = _masked_attn(q[-s_loc:], k, v, (n_sim - 1) * s_loc)
    assert_allclose(out, want, rtol=1e-4, atol=1e-4)


def _to_head_major(c):
    """(B, T, KV, hd) -> (B, KV, T, hd)."""
    return jnp.transpose(c, (0, 2, 1, 3))


def test_sp_flash_decode_fused_vs_dense(tp8_mesh, tp8_ctx):
    """Fused one-kernel split-KV decode (dense head-major cache) vs the
    dense oracle — the RDMA partial exchange replaces pmax+2 psum."""
    from triton_dist_tpu.ops import sp_flash_decode_fused

    b, h, kvh, hd, t_loc = 2, 8, 4, 16, 16
    n = 8
    key = jax.random.PRNGKey(3)
    q = jax.random.normal(key, (b, h, hd), jnp.float32) * 0.4
    k = jax.random.normal(key, (b, n * t_loc, kvh, hd), jnp.float32) * 0.4
    v = jax.random.normal(jax.random.PRNGKey(4), (b, n * t_loc, kvh, hd),
                          jnp.float32) * 0.4
    kv_len = jnp.array([n * t_loc, 37], jnp.int32)

    f = spmd(tp8_mesh,
             lambda a, kc, vc, l: sp_flash_decode_fused(
                 a, kc, vc, l, ctx=tp8_ctx, axis="tp", page=8),
             (P(None, None, None), P(None, None, "tp", None),
              P(None, None, "tp", None), P(None)),
             P(None, None, None))
    got = f(q, _to_head_major(k), _to_head_major(v), kv_len)
    expected = flash_decode_ref(q, k, v, kv_len)
    assert_allclose(got, expected, rtol=2e-4, atol=2e-4)


def test_sp_flash_decode_fused_multislice(dp2tp4_mesh, dp2tp4_ctx):
    """Hierarchical (dcn x ici) fused decode: inner-axis partials merge
    before one combined partial per outer peer crosses the slow link."""
    from triton_dist_tpu.ops import sp_flash_decode_fused

    b, h, kvh, hd, t_loc = 2, 4, 2, 16, 16
    n = 8
    key = jax.random.PRNGKey(5)
    q = jax.random.normal(key, (b, h, hd), jnp.float32) * 0.4
    k = jax.random.normal(key, (b, n * t_loc, kvh, hd), jnp.float32) * 0.4
    v = jax.random.normal(jax.random.PRNGKey(6), (b, n * t_loc, kvh, hd),
                          jnp.float32) * 0.4
    kv_len = jnp.array([91, 64], jnp.int32)

    f = spmd(dp2tp4_mesh,
             lambda a, kc, vc, l: sp_flash_decode_fused(
                 a, kc, vc, l, ctx=dp2tp4_ctx, axis=("dp", "tp"), page=8),
             (P(None, None, None), P(None, None, ("dp", "tp"), None),
              P(None, None, ("dp", "tp"), None), P(None)),
             P(None, None, None))
    got = f(q, _to_head_major(k), _to_head_major(v), kv_len)
    expected = flash_decode_ref(q, k, v, kv_len)
    assert_allclose(got, expected, rtol=2e-4, atol=2e-4)


def test_sp_flash_decode_fused_sim_ranks():
    """Self-sim exchange on one device: full schedule/traffic, output
    must equal the local dense result (LSE-combine of n identical
    partials is the identity)."""
    import numpy as np
    from jax.sharding import Mesh
    from triton_dist_tpu.parallel.mesh import MeshContext
    from triton_dist_tpu.ops import sp_flash_decode_fused

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("sp",))
    ctx1 = MeshContext.from_mesh(mesh1)
    b, h, kvh, hd, t = 2, 4, 2, 16, 32
    key = jax.random.PRNGKey(7)
    q = jax.random.normal(key, (b, h, hd), jnp.float32) * 0.4
    k = jax.random.normal(key, (b, t, kvh, hd), jnp.float32) * 0.4
    v = jax.random.normal(jax.random.PRNGKey(8), (b, t, kvh, hd),
                          jnp.float32) * 0.4
    kv_len = jnp.array([t, 19], jnp.int32)

    f = spmd(mesh1,
             lambda a, kc, vc, l: sp_flash_decode_fused(
                 a, kc, vc, l, ctx=ctx1, axis="sp", page=8, sim_ranks=4),
             (P(None, None, None), P(None, None, None, None),
              P(None, None, None, None), P(None)),
             P(None, None, None))
    got = f(q, _to_head_major(k), _to_head_major(v), kv_len)
    expected = flash_decode_ref(q, k, v, kv_len)
    assert_allclose(got, expected, rtol=2e-4, atol=2e-4)
