"""Megakernel: one persistent kernel per device must reproduce the
layer-by-layer decode step (reference acceptance: megakernel output vs
triton_dist layer path, ``mega_triton_kernel/test/models/``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
import jax.numpy as jnp

from triton_dist_tpu.layers import tp_attn, tp_mlp
from triton_dist_tpu.layers.norm import rms_norm
from triton_dist_tpu.megakernel import ModelBuilder, schedule
from triton_dist_tpu.megakernel.graph import Graph
from triton_dist_tpu.megakernel.task import TaskType
from triton_dist_tpu.models import dense
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.utils.testing import spmd, assert_allclose

CFG = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                       intermediate_size=32, num_hidden_layers=2,
                       num_attention_heads=4, num_key_value_heads=2,
                       head_dim=8)
B, MAXLEN, NTP = 2, 32, 2


def test_scheduler_native():
    """C++ scheduler: topological order + cycle detection."""
    s = schedule(4, [0, 1, 2], [1, 2, 3], num_cores=1)
    assert list(s["order"]) == [0, 1, 2, 3]
    with pytest.raises(ValueError, match="cycle"):
        schedule(2, [0, 1], [1, 0], num_cores=1)
    # Multi-core packing keeps deps cross-core.
    s = schedule(4, [0, 1], [2, 3], num_cores=2)
    assert sorted(s["order"]) == [0, 1, 2, 3]


def test_scheduler_mc_merged_order_safety():
    """tdt_schedule_mc: every task's merged index exceeds all its
    predecessors' (the no-deadlock-under-sequential guarantee), and
    cross-core edges carry wait/signal entries."""
    from triton_dist_tpu.megakernel.scheduler import schedule_mc

    # Diamond + chain: 0→1, 0→2, 1→3, 2→3, 3→4.
    s = schedule_mc(5, [0, 0, 1, 2, 3], [1, 2, 3, 3, 4], num_cores=2)
    q = s["queue"]
    merged = {}
    for qi in range(q.shape[0]):
        for c in range(2):
            t = q[qi, c]
            if t >= 0:
                merged[int(t)] = qi * 2 + c
    assert sorted(merged) == [0, 1, 2, 3, 4]
    for a, b2 in [(0, 1), (0, 2), (1, 3), (2, 3), (3, 4)]:
        assert merged[b2] > merged[a]
    # waits == signals overall, and every cross-core edge has both.
    assert s["n_edges"] == len(s["wait_edges"]) == len(s["sig_edges"])
    with pytest.raises(ValueError, match="cycle"):
        schedule_mc(2, [0, 1], [1, 0], num_cores=2)


def test_scheduler_mc_pinning_and_cost():
    from triton_dist_tpu.megakernel.scheduler import schedule_mc

    # Independent tasks; pin task 2 to core 0; heavy task 3.
    s = schedule_mc(4, [], [], num_cores=2, strategy="cost_lpt",
                    task_cost=[1, 1, 1, 100], pin_core=[-1, -1, 0, -1])
    q = s["queue"]
    core = {}
    for qi in range(q.shape[0]):
        for c in range(2):
            if q[qi, c] >= 0:
                core[int(q[qi, c])] = c
    assert core[2] == 0
    # LPT actually balances: after the heavy task lands on a core, the
    # remaining 1-cost tasks all go to the other core.
    heavy_core = core[3]
    light = [core[t_] for t_ in (0, 1) ] + [core[2]]
    assert sum(1 for c in light if c != heavy_core) >= 2


def test_graph_dataflow_deps():
    g = Graph()
    t0 = g.add(TaskType.RMSNORM, (0, 0, 10, 1), reads=[(0, 2)],
               writes=[(10, 2)])
    t1 = g.add(TaskType.LINEAR, (10, 0, 20, 1, 1, 0), reads=[(10, 2)],
               writes=[(20, 2)])
    t2 = g.add(TaskType.ADD, (0, 20, 10, 1), reads=[(0, 2), (20, 2)],
               writes=[(10, 2)])  # WAR on t1's read of 10
    assert t1.deps == [t0.task_id]
    assert t0.task_id in t2.deps or t1.task_id in t2.deps


@pytest.fixture(scope="module")
def tp2_mesh():
    return Mesh(np.array(jax.devices()[:NTP]), ("tp",))


@pytest.mark.parametrize("cores,strategy,schedule", [
    (1, "round_robin", "static"),
    (2, "round_robin", "static"),
    (2, "cost_lpt", "static"),
    (1, "round_robin", "dynamic"),
    (2, "cost_lpt", "dynamic"),
])
def test_megakernel_decode_vs_layers(tp2_mesh, cores, strategy,
                                     schedule):
    mesh = tp2_mesh
    mb = ModelBuilder(CFG, mesh, batch=B, max_len=MAXLEN, tile_w=16,
                      t_tile=16, num_cores=cores, strategy=strategy,
                      schedule=schedule)
    if schedule == "dynamic":
        # The claim list covers every task exactly once, and with
        # multiple cores the cross-core claim edges really exist.
        claimed = sorted(int(t) for t in mb.claims.reshape(-1)
                         if t >= 0)
        assert claimed == list(range(len(mb.graph.tasks)))
        if cores > 1:
            assert mb.n_edges > 0
    if schedule == "static" and cores > 1:
        # The padded schedule really uses both queues and emits a
        # scoreboard.
        assert (mb.task_types != int(TaskType.NOOP)).any(axis=1).all()
        assert mb.n_edges > 0
        assert (np.asarray(mb.task_types)[:, 1]
                != int(TaskType.NOOP)).any()
    params = dense.init_params(jax.random.PRNGKey(0), CFG)
    specs = dense.param_specs(CFG)

    kv_loc = CFG.num_key_value_heads // NTP
    cache_shape = (CFG.num_hidden_layers, B, MAXLEN,
                   CFG.num_key_value_heads, CFG.head_dim)
    k_cache = jax.random.normal(jax.random.PRNGKey(1), cache_shape) * 0.3
    v_cache = jax.random.normal(jax.random.PRNGKey(2), cache_shape) * 0.3
    tokens = jnp.asarray([3, 17], jnp.int32)
    pos = jnp.asarray(5, jnp.int32)
    kvspec = P(None, None, None, "tp", None)

    # --- megakernel path (embedding + stack + LM head in-kernel) ---
    pack = spmd(mesh, mb.pack_arena, (specs,), P("tp", None))
    arena = pack(params)
    step = spmd(mesh, mb.step_fn(),
                (P("tp", None), kvspec, kvspec, P(None), P()),
                (P(None, "tp"), P("tp", None), kvspec, kvspec))
    logits, arena2, kc2, vc2 = step(arena, k_cache, v_cache, tokens, pos)

    # --- layer-by-layer oracle (xla mode, proven against dense) ---
    def oracle(p, tok, kc, vc):
        h = p["embed"][tok]
        new_k, new_v = kc, vc
        for li, lp in enumerate(p["layers"]):
            t = rms_norm(h, lp["ln_attn"], CFG.rms_norm_eps)
            ao, (lk, lv) = tp_attn.fwd_decode(
                lp["attn"], t, CFG, new_k[li], new_v[li], pos, mode="xla")
            new_k = new_k.at[li].set(lk)
            new_v = new_v.at[li].set(lv)
            h = h + ao
            t = rms_norm(h, lp["ln_mlp"], CFG.rms_norm_eps)
            h = h + tp_mlp.fwd(lp["mlp"], t, mode="xla_ar")
        h = rms_norm(h, p["ln_f"], CFG.rms_norm_eps)
        logits_loc = h @ p["lm_head"].T
        return (jax.lax.all_gather(logits_loc, "tp", axis=1, tiled=True),
                new_k, new_v)

    of = spmd(mesh, oracle, (specs, P(None), kvspec, kvspec),
              (P(None, None), kvspec, kvspec))
    want_logits, want_k, want_v = of(params, tokens, k_cache, v_cache)

    assert_allclose(logits, want_logits, rtol=2e-3, atol=2e-3)
    # Cache slot 5 must hold the new roped+normed K and the raw V.
    assert_allclose(np.asarray(kc2)[:, :, 5], np.asarray(want_k)[:, :, 5],
                    rtol=2e-3, atol=2e-3)
    assert_allclose(np.asarray(vc2)[:, :, 5], np.asarray(want_v)[:, :, 5],
                    rtol=2e-3, atol=2e-3)
    # Untouched slots unchanged.
    assert_allclose(np.asarray(kc2)[:, :, :5], np.asarray(k_cache)[:, :, :5])



def _layer_engine_greedy(engine, cfg, seed_tok, steps):
    """Greedy decode chain through the layer Engine from an empty cache
    (the megakernel tests' shared oracle)."""
    from triton_dist_tpu.models.kv_cache import KVCache

    cache = KVCache.empty(cfg.num_hidden_layers, seed_tok.shape[0],
                          MAXLEN, cfg.num_key_value_heads, cfg.head_dim)
    tok = seed_tok
    ref = []
    for _ in range(steps):
        logits, cache = engine._decode(engine.params, tok, cache)
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        ref.append(np.asarray(tok))
    return np.stack(ref, axis=1)


def test_megakernel_engine_generate(tp2_mesh):
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    eng = MegaKernelEngine(CFG, tp2_mesh, batch=B, max_len=MAXLEN,
                           tile_w=16, t_tile=16, seed=4,
                           keep_params=True)
    toks = np.asarray(eng.generate(jnp.zeros((B,), jnp.int32), steps=4))
    assert toks.shape == (B, 4)
    assert np.isfinite(toks).all()

    # Oracle: same params through the layer-path Engine decode chain
    # (a decode at position 0 on an empty cache == the seed prefill).
    from triton_dist_tpu.models import Engine
    params = jax.tree.map(np.asarray, eng.params)
    e2 = Engine(CFG, tp2_mesh, mode="xla", max_len=MAXLEN, params=params)
    ref = _layer_engine_greedy(e2, CFG, jnp.zeros((B,), jnp.int32), 4)
    np.testing.assert_array_equal(toks, ref)


def test_megakernel_batched_prefill(tp2_mesh):
    """One batched-prefill launch == the token-by-token decode chain
    (logits at the last position AND the whole written cache)."""
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    S = 4
    eng = MegaKernelEngine(CFG, tp2_mesh, batch=B, max_len=MAXLEN,
                           tile_w=16, t_tile=16, seed=7,
                           keep_params=True, prefill_seq=S)
    prompts = jnp.asarray([[3, 9, 1, 12], [5, 0, 7, 2]], jnp.int32)
    logits = np.asarray(eng.prefill(prompts))
    kc_pref = np.asarray(eng.k_cache)
    vc_pref = np.asarray(eng.v_cache)

    # Oracle: a second engine feeding the same prompt token-by-token.
    eng2 = MegaKernelEngine(CFG, tp2_mesh, batch=B, max_len=MAXLEN,
                            tile_w=16, t_tile=16, seed=7,
                            keep_params=True)
    for pos in range(S - 1):
        eng2.decode_step(prompts[:, pos], pos)
    want = np.asarray(eng2.decode_step(prompts[:, -1], S - 1))

    np.testing.assert_allclose(logits, want, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(kc_pref[:, :, :S],
                               np.asarray(eng2.k_cache)[:, :, :S],
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(vc_pref[:, :, :S],
                               np.asarray(eng2.v_cache)[:, :, :S],
                               rtol=2e-3, atol=2e-3)

    # Decode continues from the batched prefill seamlessly.
    nxt = jnp.argmax(jnp.asarray(logits), -1).astype(jnp.int32)
    l2 = np.asarray(eng.decode_step(nxt, S))
    nxt2 = jnp.argmax(jnp.asarray(want), -1).astype(jnp.int32)
    w2 = np.asarray(eng2.decode_step(nxt2, S))
    np.testing.assert_allclose(l2, w2, rtol=2e-3, atol=2e-3)


def test_megakernel_paged_vs_dense(tp2_mesh):
    """Paged KV (pool + block table) must reproduce the dense-cache
    engine exactly: batched prefill, then decode steps, including a
    NON-identity block table (pages physically shuffled in the pool)."""
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    S = 4
    kw = dict(batch=B, max_len=MAXLEN, tile_w=16, t_tile=8, seed=9,
              keep_params=True, prefill_seq=S)
    dense_eng = MegaKernelEngine(CFG, tp2_mesh, **kw)
    paged_eng = MegaKernelEngine(CFG, tp2_mesh, paged=True, page=8,
                                 **kw)
    p_max = paged_eng.builder.p_max
    assert p_max == MAXLEN // 8

    # Scramble the pool: reverse the identity table (still a bijection).
    n_slots = B * p_max
    paged_eng.block_table = jnp.asarray(
        np.arange(n_slots)[::-1].copy(), jnp.int32)

    prompts = jnp.asarray([[3, 9, 1, 12], [5, 0, 7, 2]], jnp.int32)
    lp = np.asarray(paged_eng.prefill(prompts))
    ld = np.asarray(dense_eng.prefill(prompts))
    np.testing.assert_allclose(lp, ld, rtol=2e-3, atol=2e-3)

    tok = jnp.argmax(jnp.asarray(ld), -1).astype(jnp.int32)
    for i in range(6):  # positions 4..9: writes cross into page 1 at 8
        l2p = np.asarray(paged_eng.decode_step(tok, S + i))
        l2d = np.asarray(dense_eng.decode_step(tok, S + i))
        np.testing.assert_allclose(l2p, l2d, rtol=2e-3, atol=2e-3)
        tok = jnp.argmax(jnp.asarray(l2d), -1).astype(jnp.int32)


def test_megakernel_moe_decode_vs_layers(tp2_mesh):
    """MoE megakernel: in-kernel router + all-expert swiglu + weighted
    combine must match the layer oracle (tp_moe.fwd_ar — the same
    all-expert small-batch math)."""
    from triton_dist_tpu.layers import tp_moe
    from triton_dist_tpu.models import qwen_moe

    mcfg = ModelConfig.tiny_moe(vocab_size=64, hidden_size=32,
                                num_hidden_layers=2,
                                num_attention_heads=4,
                                num_key_value_heads=2, head_dim=8,
                                num_experts=4, num_experts_per_tok=2,
                                moe_intermediate_size=32)
    mesh = tp2_mesh
    mb = ModelBuilder(mcfg, mesh, batch=B, max_len=MAXLEN, tile_w=16,
                      t_tile=16)
    assert mb.moe and (mb.task_types == int(TaskType.MOE_WEIGHTS)).sum()
    params = qwen_moe.init_params(jax.random.PRNGKey(3), mcfg)
    specs = qwen_moe.param_specs(mcfg, moe_impl="tp")

    cache_shape = (mcfg.num_hidden_layers, B, MAXLEN,
                   mcfg.num_key_value_heads, mcfg.head_dim)
    k_cache = jax.random.normal(jax.random.PRNGKey(4), cache_shape) * 0.3
    v_cache = jax.random.normal(jax.random.PRNGKey(5), cache_shape) * 0.3
    tokens = jnp.asarray([9, 41], jnp.int32)
    pos = jnp.asarray(5, jnp.int32)
    kvspec = P(None, None, None, "tp", None)

    pack = spmd(mesh, mb.pack_arena, (specs,), P("tp", None))
    arena = pack(params)
    step = spmd(mesh, mb.step_fn(),
                (P("tp", None), kvspec, kvspec, P(None), P()),
                (P(None, "tp"), P("tp", None), kvspec, kvspec))
    logits, _, _, _ = step(arena, k_cache, v_cache, tokens, pos)

    def oracle(p, tok, kc, vc):
        h = p["embed"][tok]
        new_k, new_v = kc, vc
        for li, lp in enumerate(p["layers"]):
            t = rms_norm(h, lp["ln_attn"], mcfg.rms_norm_eps)
            ao, (lk, lv) = tp_attn.fwd_decode(
                lp["attn"], t, mcfg, new_k[li], new_v[li], pos,
                mode="xla")
            new_k = new_k.at[li].set(lk)
            new_v = new_v.at[li].set(lv)
            h = h + ao
            t = rms_norm(h, lp["ln_mlp"], mcfg.rms_norm_eps)
            h = h + tp_moe.fwd_ar(lp["moe"], t,
                                  topk=mcfg.num_experts_per_tok,
                                  num_experts=mcfg.num_experts,
                                  norm_topk_prob=mcfg.norm_topk_prob)
        h = rms_norm(h, p["ln_f"], mcfg.rms_norm_eps)
        logits_loc = h @ p["lm_head"].T
        return jax.lax.all_gather(logits_loc, "tp", axis=1, tiled=True)

    of = spmd(mesh, oracle, (specs, P(None), kvspec, kvspec),
              P(None, None))
    want = of(params, tokens, k_cache, v_cache)
    assert_allclose(logits, want, rtol=2e-3, atol=2e-3)


def test_megakernel_profile_slots(tp2_mesh):
    """profile=True: the step emits one (task_type, arg0) row per queue
    slot; core_activity computes the per-core busy fraction (the
    reference's SM-activity metric) and the rows export to Perfetto."""
    mesh = tp2_mesh
    mb = ModelBuilder(CFG, mesh, batch=B, max_len=MAXLEN, tile_w=16,
                      t_tile=16, num_cores=2, strategy="cost_lpt",
                      profile=True)
    params = dense.init_params(jax.random.PRNGKey(0), CFG)
    specs = dense.param_specs(CFG)
    cache_shape = (CFG.num_hidden_layers, B, MAXLEN,
                   CFG.num_key_value_heads, CFG.head_dim)
    k_cache = jnp.zeros(cache_shape)
    v_cache = jnp.zeros(cache_shape)
    kvspec = P(None, None, None, "tp", None)

    pack = spmd(mesh, mb.pack_arena, (specs,), P("tp", None))
    arena = pack(params)
    step = spmd(mesh, mb.step_fn(),
                (P("tp", None), kvspec, kvspec, P(None), P()),
                (P(None, "tp"), P("tp", None), kvspec, kvspec,
                 P(None, None)))
    logits, _, _, _, prof = step(arena, k_cache, v_cache,
                                 jnp.asarray([1, 2], jnp.int32),
                                 jnp.asarray(0, jnp.int32))
    prof = np.asarray(prof)
    assert prof.shape == (mb.qlen * 2, 2)
    # Every real task type in the schedule appears in the log
    # (tags are task_type + 1 — the exporter's (0,0) unused-slot
    # sentinel must never collide with RMSNORM=0 rows).
    logged = set(prof[:, 0].tolist())
    for tt in (TaskType.LINEAR, TaskType.RMSNORM, TaskType.ALLREDUCE):
        assert int(tt) + 1 in logged
    act = mb.core_activity(prof)
    assert act.shape == (2,) and (act > 0).all() and (act <= 1).all()

    # The slot log is Perfetto-exportable via the standard viewer.
    import tempfile, os, json
    from triton_dist_tpu.profiler import export_to_perfetto_trace
    with tempfile.TemporaryDirectory() as td:
        path = export_to_perfetto_trace(
            prof, os.path.join(td, "mk.json"),
            tag_names={int(t) + 1: t.name for t in TaskType})
        names = [e["name"] for e in json.load(open(path))["traceEvents"]]
    assert "LINEAR" in names


def test_megakernel_moe_paged_compose(tp2_mesh):
    """MoE task graph composes with the paged-KV cache: the paged
    engine's prefill+decode logits must MATCH the dense-cache MoE
    engine on identical params (the paged_vs_dense oracle pattern)."""
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine
    from triton_dist_tpu.models import qwen_moe

    mcfg = ModelConfig.tiny_moe(vocab_size=64, hidden_size=32,
                                num_hidden_layers=2,
                                num_attention_heads=4,
                                num_key_value_heads=2, head_dim=8,
                                num_experts=4, num_experts_per_tok=2,
                                moe_intermediate_size=32)
    params = qwen_moe.init_params(jax.random.PRNGKey(11), mcfg)
    kw = dict(batch=2, max_len=32, tile_w=16, t_tile=16,
              prefill_seq=16, params=params)
    paged = MegaKernelEngine(mcfg, tp2_mesh, paged=True, **kw)
    dense_e = MegaKernelEngine(mcfg, tp2_mesh, paged=False, **kw)

    prompts = jnp.asarray(
        np.random.RandomState(3).randint(0, mcfg.vocab_size, (2, 16)),
        jnp.int32)
    lp = paged.prefill(prompts)
    ld = dense_e.prefill(prompts)
    assert_allclose(np.asarray(lp, np.float32),
                    np.asarray(ld, np.float32), rtol=2e-3, atol=2e-3)
    tok = jnp.argmax(ld, -1).astype(jnp.int32)
    lp2 = paged.decode_step(tok, 16)
    ld2 = dense_e.decode_step(tok, 16)
    assert_allclose(np.asarray(lp2, np.float32),
                    np.asarray(ld2, np.float32), rtol=2e-3, atol=2e-3)


def test_megakernel_hybrid_gdn_decode_vs_layers(tp2_mesh):
    """Hybrid (qwen_next) decode in the megakernel: GDN layers advance
    their recurrent state via the GDN_DECODE task, softmax layers use
    the KV cache — logits and new states must match the qwen_next
    layer decode_step."""
    from triton_dist_tpu.models import qwen_next
    from triton_dist_tpu.models.kv_cache import KVCache

    hcfg = ModelConfig.tiny_next(vocab_size=64, hidden_size=32,
                                 num_hidden_layers=4,
                                 num_attention_heads=4,
                                 num_key_value_heads=2, head_dim=8,
                                 gdn_num_heads=8, gdn_head_dim_k=8,
                                 gdn_head_dim_v=8, full_attn_interval=2)
    mesh = tp2_mesh
    mb = ModelBuilder(hcfg, mesh, batch=B, max_len=MAXLEN, tile_w=16,
                      t_tile=16)
    assert mb.hybrid and (mb.task_types == int(TaskType.GDN_DECODE)
                          ).sum() == 2  # layers 0, 2
    params = qwen_next.init_params(jax.random.PRNGKey(7), hcfg)
    specs = qwen_next.param_specs(hcfg)

    n_attn, n_gdn = 2, 2
    cache_shape = (n_attn, B, MAXLEN, hcfg.num_key_value_heads,
                   hcfg.head_dim)
    k_cache = jax.random.normal(jax.random.PRNGKey(8), cache_shape) * 0.3
    v_cache = jax.random.normal(jax.random.PRNGKey(9), cache_shape) * 0.3
    states0 = jax.random.normal(
        jax.random.PRNGKey(10),
        (n_gdn, B, hcfg.gdn_num_heads, hcfg.gdn_head_dim_k,
         hcfg.gdn_head_dim_v)) * 0.2
    tokens = jnp.asarray([5, 23], jnp.int32)
    pos = jnp.asarray(5, jnp.int32)
    kvspec = P(None, None, None, "tp", None)
    stspec = P(None, None, "tp", None, None)

    pack = spmd(mesh, mb.pack_arena, (specs,), P("tp", None))
    arena = pack(params)
    step = spmd(mesh, mb.step_fn(),
                (P("tp", None), kvspec, kvspec, P(None), P(), P(None),
                 stspec),
                (P(None, "tp"), P("tp", None), kvspec, kvspec, stspec))
    logits, _, _, _, states2 = step(
        arena, k_cache, v_cache, tokens, pos, jnp.zeros((1,), jnp.int32),
        states0)

    def oracle(p, tok, kc, vc, st):
        cache = qwen_next.HybridCache(
            kv=KVCache(k=kc, v=vc, length=pos), states=st,
            conv=jnp.zeros((st.shape[0], st.shape[1], 0, 0),
                           jnp.float32))
        lg, cache2 = qwen_next.decode_step(p, tok, cache, hcfg)
        return lg, cache2.states

    of = spmd(mesh, oracle,
              (specs, P(None), kvspec, kvspec, stspec),
              (P(None, None), stspec))
    want_logits, want_states = of(params, tokens, k_cache, v_cache,
                                  states0)
    assert_allclose(logits, want_logits, rtol=2e-3, atol=2e-3)
    assert_allclose(np.asarray(states2), np.asarray(want_states),
                    rtol=2e-3, atol=2e-3)


def test_megakernel_hybrid_engine_matches_layer_engine(tp2_mesh):
    """MegaKernelEngine with a hybrid config (prefill_chain + generate)
    produces the same greedy tokens as the layer-path Engine serving
    qwen_next on identical params."""
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine
    from triton_dist_tpu.models import Engine, qwen_next

    hcfg = ModelConfig.tiny_next(vocab_size=64, hidden_size=32,
                                 num_hidden_layers=4,
                                 num_attention_heads=4,
                                 num_key_value_heads=2, head_dim=8,
                                 gdn_num_heads=8, gdn_head_dim_k=8,
                                 gdn_head_dim_v=8, full_attn_interval=2)
    params = qwen_next.init_params(jax.random.PRNGKey(12), hcfg)
    mk = MegaKernelEngine(hcfg, tp2_mesh, batch=2, max_len=32,
                          tile_w=16, t_tile=16, params=params)
    prompts = jnp.asarray(
        np.random.RandomState(5).randint(0, hcfg.vocab_size, (2, 8)),
        jnp.int32)
    seed_tok = mk.prefill_chain(prompts)
    mk_toks = np.asarray(mk.generate(seed_tok, steps=5, start_pos=7))

    eng = Engine(hcfg, tp2_mesh, mode="xla", max_len=32,
                 model=qwen_next, params=params)
    eng_toks = np.asarray(eng.serve(prompts, gen_len=5))
    np.testing.assert_array_equal(mk_toks, eng_toks)


def test_megakernel_hybrid_reset_states(tp2_mesh):
    """Reusing a hybrid engine for a second independent prompt must
    reproduce the fresh-engine tokens after reset_states() (stale
    recurrent state has no position mask, unlike KV rows)."""
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine
    from triton_dist_tpu.models import qwen_next

    hcfg = ModelConfig.tiny_next(vocab_size=64, hidden_size=32,
                                 num_hidden_layers=2,
                                 num_attention_heads=4,
                                 num_key_value_heads=2, head_dim=8,
                                 gdn_num_heads=4, gdn_head_dim_k=8,
                                 gdn_head_dim_v=8, full_attn_interval=2)
    params = qwen_next.init_params(jax.random.PRNGKey(30), hcfg)
    eng = MegaKernelEngine(hcfg, tp2_mesh, batch=2, max_len=32,
                           tile_w=16, t_tile=16, params=params)
    p1 = jnp.asarray([[3, 9, 27, 17], [5, 25, 61, 41]], jnp.int32)
    p2 = jnp.asarray([[8, 16, 32, 60], [7, 49, 23, 11]], jnp.int32)
    eng.generate(eng.prefill_chain(p1), steps=3, start_pos=3)

    eng.reset_states()
    t2_reused = np.asarray(
        eng.generate(eng.prefill_chain(p2), steps=3, start_pos=3))

    fresh = MegaKernelEngine(hcfg, tp2_mesh, batch=2, max_len=32,
                             tile_w=16, t_tile=16, params=params)
    t2_fresh = np.asarray(
        fresh.generate(fresh.prefill_chain(p2), steps=3, start_pos=3))
    np.testing.assert_array_equal(t2_reused, t2_fresh)


def test_profile_feedback_rescheduling_improves_activity(tp2_mesh):
    """Profile-feedback loop (reference enable_runtime_scheduler,
    answered at schedule time): a cost_lpt build whose cost table is
    miscalibrated (all types weighted to ~nothing, collapsing LPT to
    slot-filling) is re-scheduled with calibrated weights — the second
    build must strictly beat the first on mean core activity and stay
    numerically identical."""
    from triton_dist_tpu.megakernel.builder import calibrate_cost_table

    mesh = tp2_mesh

    def build(table):
        return ModelBuilder(CFG, mesh, batch=B, max_len=MAXLEN,
                            tile_w=16, t_tile=16, num_cores=2,
                            strategy="cost_lpt", profile=True,
                            cost_table=table)

    # "First run": a badly calibrated table (every unit ~free).
    bad = {int(tt): 1e-6 for tt in TaskType}
    mb_bad = build(bad)

    # "Measured feedback": synthetic wall times at 1 time-unit per work
    # unit (what silicon timing would show if the static estimates were
    # perfect), over a FULL-RANK observation mix — the base build plus
    # one build-variant per type with that type's count scaled up.
    mb_probe = ModelBuilder(CFG, mesh, batch=B, max_len=MAXLEN,
                            tile_w=16, t_tile=16, num_cores=2,
                            strategy="cost_lpt")
    c1 = mb_probe.task_unit_counts()
    unit_ns = 3.7e-9
    obs = [(c1, sum(c1.values()) * unit_ns)]
    for k in c1:
        c = dict(c1)
        c[k] = c1[k] * 3
        obs.append((c, sum(c.values()) * unit_ns))
    table = calibrate_cost_table(obs)
    # Perfect static estimates -> ~uniform per-unit weights.
    assert all(abs(w - 1.0) < 1e-6 for w in table.values()), table
    assert all(w >= 0 for w in table.values())
    mb_good = build(table)

    # Calibrated schedule is at least as balanced, and strictly better
    # than the degenerate one.
    params = dense.init_params(jax.random.PRNGKey(0), CFG)
    specs = dense.param_specs(CFG)
    cache_shape = (CFG.num_hidden_layers, B, MAXLEN,
                   CFG.num_key_value_heads, CFG.head_dim)
    k_cache = jnp.zeros(cache_shape)
    v_cache = jnp.zeros(cache_shape)
    kvspec = P(None, None, None, "tp", None)
    toks = jnp.asarray([1, 2], jnp.int32)

    acts, logits_out = [], []
    for mb in (mb_bad, mb_good):
        pack = spmd(mesh, mb.pack_arena, (specs,), P("tp", None))
        arena = pack(params)
        step = spmd(mesh, mb.step_fn(),
                    (P("tp", None), kvspec, kvspec, P(None), P()),
                    (P(None, "tp"), P("tp", None), kvspec, kvspec,
                     P(None, None)))
        logits, _, _, _, prof = step(arena, k_cache, v_cache, toks,
                                     jnp.asarray(0, jnp.int32))
        acts.append(float(np.mean(mb.core_activity(prof))))
        logits_out.append(np.asarray(logits))
    np.testing.assert_allclose(logits_out[0], logits_out[1],
                               rtol=1e-5, atol=1e-5)
    assert acts[1] > acts[0], (acts, mb_bad.qlen, mb_good.qlen)


def test_calibrate_cost_table_recovers_weights():
    """lstsq recovery: synthetic observations from known per-unit
    times must reproduce their ratios."""
    from triton_dist_tpu.megakernel.builder import calibrate_cost_table

    truth = {0: 1.0, 3: 4.0, 7: 2.5}
    rng = np.random.default_rng(0)
    obs = []
    for _ in range(6):
        counts = {k: int(rng.integers(5, 50)) for k in truth}
        wall = sum(truth[k] * v for k, v in counts.items()) * 1e-7
        obs.append((counts, wall))
    table = calibrate_cost_table(obs)
    assert abs(table[3] / table[0] - 4.0) < 1e-6
    assert abs(table[7] / table[0] - 2.5) < 1e-6


def test_perfetto_export_labels_timing_model(tp2_mesh):
    """Timing honesty (VERDICT r4 weak #5): the default export labels
    every event 'reconstructed' (program order, no duration claim); an
    export fed by the calibrated cost model emits spans labeled
    'calibrated' with durations from the model."""
    import json
    import os
    import tempfile

    from triton_dist_tpu.megakernel.builder import calibrate_cost_table
    from triton_dist_tpu.profiler import export_to_perfetto_trace

    mesh = tp2_mesh
    mb = ModelBuilder(CFG, mesh, batch=B, max_len=MAXLEN, tile_w=16,
                      t_tile=16, num_cores=2, strategy="cost_lpt",
                      profile=True)
    # Synthetic measured observations (full-rank mix) -> calibrated
    # per-type weights. Rank-deficient mixes must raise, not fit.
    c1 = mb.task_unit_counts()
    with pytest.raises(ValueError, match="rank"):
        calibrate_cost_table(
            [(c1, 1.0), ({k: v * 2 for k, v in c1.items()}, 2.0)])
    obs = [(c1, sum(c1.values()) * 2e-9)]
    for k in c1:
        c = dict(c1)
        c[k] = c1[k] * 3
        obs.append((c, sum(c.values()) * 2e-9))
    table = calibrate_cost_table(obs)
    durs = mb.slot_durations(table, unit_s=2e-9)
    assert durs.shape == (2, mb.qlen)

    # A REAL step's profile output through the prof_tracks adapter.
    params = dense.init_params(jax.random.PRNGKey(0), CFG)
    specs = dense.param_specs(CFG)
    cache_shape = (CFG.num_hidden_layers, B, MAXLEN,
                   CFG.num_key_value_heads, CFG.head_dim)
    kvspec = P(None, None, None, "tp", None)
    pack = spmd(mesh, mb.pack_arena, (specs,), P("tp", None))
    arena = pack(params)
    step = spmd(mesh, mb.step_fn(),
                (P("tp", None), kvspec, kvspec, P(None), P()),
                (P(None, "tp"), P("tp", None), kvspec, kvspec,
                 P(None, None)))
    _, _, _, _, prof = step(arena, jnp.zeros(cache_shape),
                            jnp.zeros(cache_shape),
                            jnp.asarray([1, 2], jnp.int32),
                            jnp.asarray(0, jnp.int32))
    tracks = mb.prof_tracks(prof)
    assert tracks.shape == (2, mb.qlen, 2)
    with tempfile.TemporaryDirectory() as td:
        p1 = export_to_perfetto_trace(
            tracks, os.path.join(td, "recon.json"),
            tag_names={int(t) + 1: t.name for t in TaskType})
        ev1 = json.load(open(p1))["traceEvents"]
        p2 = export_to_perfetto_trace(
            tracks, os.path.join(td, "calib.json"),
            tag_names={int(t) + 1: t.name for t in TaskType},
            slot_durations=durs)
        ev2 = json.load(open(p2))["traceEvents"]
    assert all(e["args"]["timing"] == "reconstructed"
               for e in ev1 if "value" in e.get("args", {}))
    spans = [e for e in ev2 if e["ph"] == "X"]
    assert spans and all(e["args"]["timing"] == "calibrated"
                         for e in spans)
    assert any(e["dur"] > 0 for e in spans)


def _graph_cases():
    """Synthetic dependency graphs for the scheduler sweeps."""
    chain = ([0, 1, 2], [1, 2, 3], 4)
    diamond = ([0, 0, 1, 2, 3], [1, 2, 3, 3, 4], 5)
    # Skewed: a heavy chain plus a crowd of light independents.
    sk_src = [0, 1, 2]
    sk_dst = [1, 2, 3]
    skewed = (sk_src, sk_dst, 12)
    wide = ([0] * 6, list(range(1, 7)), 8)
    return {"chain": chain, "diamond": diamond, "skewed": skewed,
            "wide": wide}


@pytest.mark.parametrize("gname", sorted(_graph_cases()))
@pytest.mark.parametrize("cores", [1, 2, 3, 4])
def test_scheduler_fairness_every_task_claimed_once(gname, cores):
    """Starvation sweep: across every (graph, core count, priority
    bucket) combination — including adversarial priorities that starve
    a bucket if the claim loop ever could — each task is claimed
    exactly once, holes only arise from pinning, and the claim order
    is topologically valid."""
    from triton_dist_tpu.megakernel.scheduler import schedule_dyn

    src, dst, n = _graph_cases()[gname]
    rng = np.random.RandomState(hash(gname) % 2 ** 16)
    for trial in range(3):
        prio = rng.randint(0, 1 << 20, size=n)
        bkt = rng.randint(0, 3, size=n)
        pin = np.where(rng.rand(n) < 0.3,
                       rng.randint(0, cores, size=n), -1)
        d = schedule_dyn(n, src, dst, num_cores=cores, priority=prio,
                         bucket=bkt, task_cost=rng.randint(1, 50, n),
                         pin_core=pin)
        order = d["claim_order"]
        claimed = sorted(int(t) for t in order if t >= 0)
        assert claimed == list(range(n)), (gname, cores, trial)
        # claim_of inverts claim_order.
        for i, t in enumerate(order):
            if t >= 0:
                assert d["claim_of"][t] == i
        # Topological validity + pinning honored.
        pos = {int(t): i for i, t in enumerate(order) if t >= 0}
        for a, b2 in zip(src, dst):
            assert pos[b2] > pos[a]
        for t in range(n):
            if pin[t] >= 0:
                assert pos[t] % cores == pin[t] % cores
        # Holes can only come from pinning.
        if (pin < 0).all():
            assert (order >= 0).all()
        # Every cross-core wait has a matching signal.
        assert d["n_edges"] == len(d["wait_edges"]) == len(
            d["sig_edges"])


def test_dynamic_beats_cost_lpt_on_skewed_graph():
    """The acceptance comparison: on a skewed-cost graph the dynamic
    claim schedule must show strictly fewer idle scoreboard steps (NOOP
    slots) AND a strictly better timed model than cost_lpt — the
    static packer balances total load blind to readiness, so the heavy
    chain serializes behind padding."""
    from triton_dist_tpu.megakernel.graph import comm_priority
    from triton_dist_tpu.megakernel.scheduler import (
        prune_deps, schedule_dyn, schedule_mc, simulate_static)
    from triton_dist_tpu.megakernel.task import Task

    # Heavy chain 0->1->2->3 (cost 40 each) + 8 light independents.
    src = [0, 1, 2]
    dst = [1, 2, 3]
    n = 12
    cost = [40, 40, 40, 40] + [10] * 8
    tasks = [Task(task_id=i, task_type=TaskType.LINEAR, args=(),
                  deps=([i - 1] if 1 <= i <= 3 else []))
             for i in range(n)]
    prio, bkt, _ = comm_priority(tasks, n_ranks=1, task_cost=cost)
    # Critical-path priority must rank the chain head first.
    assert prio[0] == max(prio)

    s = schedule_mc(n, src, dst, num_cores=2, strategy="cost_lpt",
                    task_cost=cost)
    ps, pd = prune_deps(n, src, dst)
    stat = simulate_static(n, ps, pd, s["queue"], task_cost=cost)
    d = schedule_dyn(n, src, dst, num_cores=2, priority=prio,
                     bucket=bkt, task_cost=cost)

    static_noops = int((s["queue"] < 0).sum())
    dyn_slots = -(-d["n_claims"] // 2) * 2
    dyn_noops = int((d["claim_order"] < 0).sum()) + dyn_slots - d[
        "n_claims"]
    assert dyn_noops < static_noops, (dyn_noops, static_noops)
    assert d["idle_units"] < stat["idle_units"], (d, stat)
    assert d["makespan"] <= stat["makespan"], (d, stat)


def test_dynamic_fewer_idle_steps_interpret_counter(tp2_mesh):
    """Model-level skewed-cost comparison scored on the INTERPRET-MODE
    step counter: a profiled step executes strictly fewer NOOP slots
    under the dynamic scheduler than under cost_lpt when the cost
    table is skewed (LINEAR weighted heavy)."""
    mesh = tp2_mesh
    skew = {int(tt): 1.0 for tt in TaskType}
    skew[int(TaskType.LINEAR)] = 8.0
    noops = {}
    for schedule in ("static", "dynamic"):
        mb = ModelBuilder(CFG, mesh, batch=B, max_len=MAXLEN, tile_w=16,
                          t_tile=16, num_cores=2, strategy="cost_lpt",
                          schedule=schedule, profile=True,
                          cost_table=skew)
        params = dense.init_params(jax.random.PRNGKey(0), CFG)
        specs = dense.param_specs(CFG)
        cache_shape = (CFG.num_hidden_layers, B, MAXLEN,
                       CFG.num_key_value_heads, CFG.head_dim)
        kvspec = P(None, None, None, "tp", None)
        pack = spmd(mesh, mb.pack_arena, (specs,), P("tp", None))
        arena = pack(params)
        step = spmd(mesh, mb.step_fn(),
                    (P("tp", None), kvspec, kvspec, P(None), P()),
                    (P(None, "tp"), P("tp", None), kvspec, kvspec,
                     P(None, None)))
        _, _, _, _, prof = step(arena, jnp.zeros(cache_shape),
                                jnp.zeros(cache_shape),
                                jnp.asarray([1, 2], jnp.int32),
                                jnp.asarray(0, jnp.int32))
        prof = np.asarray(prof)
        executed_noops = int(
            (prof[:, 0] == int(TaskType.NOOP) + 1).sum())
        assert executed_noops == mb.noop_slots()
        noops[schedule] = executed_noops
        # The profile-feedback fold sees exactly the executed units.
        assert mb.profile_unit_counts(prof) == mb.task_unit_counts()
    assert noops["dynamic"] < noops["static"], noops


def test_megakernel_dynamic_token_exact_all_families(tp2_mesh):
    """Acceptance: schedule="dynamic" produces token-exact greedy
    output vs static on the dense, MoE, and hybrid-GDN families."""
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine
    from triton_dist_tpu.models import qwen_moe, qwen_next

    mcfg = ModelConfig.tiny_moe(vocab_size=64, hidden_size=32,
                                num_hidden_layers=2,
                                num_attention_heads=4,
                                num_key_value_heads=2, head_dim=8,
                                num_experts=4, num_experts_per_tok=2,
                                moe_intermediate_size=32)
    hcfg = ModelConfig.tiny_next(vocab_size=64, hidden_size=32,
                                 num_hidden_layers=4,
                                 num_attention_heads=4,
                                 num_key_value_heads=2, head_dim=8,
                                 gdn_num_heads=8, gdn_head_dim_k=8,
                                 gdn_head_dim_v=8, full_attn_interval=2)
    fams = [("dense", CFG, None),
            ("moe", mcfg, qwen_moe),
            ("hybrid", hcfg, qwen_next)]
    for name, cfg, model in fams:
        params = (model.init_params(jax.random.PRNGKey(21), cfg)
                  if model is not None
                  else dense.init_params(jax.random.PRNGKey(21), cfg))
        toks = {}
        for schedule in ("static", "dynamic"):
            eng = MegaKernelEngine(cfg, tp2_mesh, batch=B, max_len=32,
                                   tile_w=16, t_tile=16, params=params,
                                   num_cores=2, strategy="cost_lpt",
                                   schedule=schedule)
            toks[schedule] = np.asarray(
                eng.generate(jnp.asarray([3, 7], jnp.int32), steps=4))
        np.testing.assert_array_equal(
            toks["static"], toks["dynamic"],
            err_msg=f"dynamic schedule diverged on {name}")


def test_dynamic_dropped_edge_terminates_or_raises(tp2_mesh):
    """Fault-injection gate: a dropped scoreboard edge under the
    dynamic scheduler must terminate (the compat interpreter's
    semaphores never block) or raise — never livelock. The Watchdog
    deadline converts a livelock into a hard failure."""
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine
    from triton_dist_tpu.resilience import CommTimeoutError, faults
    from triton_dist_tpu.resilience.watchdog import Watchdog

    plan = faults.get_plan("dropped_edge", op="megakernel", k=0)
    with faults.inject(plan):
        eng = MegaKernelEngine(CFG, tp2_mesh, batch=B, max_len=32,
                               tile_w=16, t_tile=16, seed=4,
                               num_cores=2, schedule="dynamic")
        assert eng.builder.n_edges > 0  # the plan has an edge to drop
        try:
            toks = Watchdog(120.0, op="megakernel.dynamic").run(
                lambda: np.asarray(eng.generate(
                    jnp.zeros((B,), jnp.int32), steps=2)))
        except CommTimeoutError as e:
            # A blocking backend wedges on the missing signal — the
            # structured timeout IS the accepted outcome there.
            assert e.op == "megakernel.dynamic"
            return
    # Non-blocking backend: the run must have terminated with sane
    # output and the claim-counter progress must be intact.
    assert toks.shape == (B, 2)
    prog = eng.progress()
    assert prog["progress_counter"] == "claim"
    assert prog["steps_done"] == 2


def test_describe_slot_dynamic_and_claim():
    """describe_slot on a dynamic schedule attributes (q, c) as a
    claim-counter value: claimed task id, priority bucket, and edge
    semaphores — not a static queue position."""
    from triton_dist_tpu.megakernel.scheduler import (
        describe_claim, schedule_dyn)

    src, dst = [0, 0, 1, 2], [1, 2, 3, 3]
    d = schedule_dyn(4, src, dst, num_cores=2,
                     priority=[3, 2, 1, 0], bucket=[0, 0, 1, 1])
    seen = set()
    for claim in range(d["n_claims"]):
        desc = describe_claim(d, claim)
        assert desc["schedule"] == "dynamic"
        assert desc["claim"] == claim
        assert desc["core"] == claim % 2
        if desc["task"] >= 0:
            seen.add(desc["task"])
            assert "bucket" in desc
    assert seen == {0, 1, 2, 3}
    from triton_dist_tpu.megakernel.scheduler import describe_slot
    assert describe_slot(d, 0, 1) == describe_claim(d, 1)
    # Tail padding past n_claims is named, not an error.
    tail = describe_claim(d, d["n_claims"] + 1)
    assert tail["task"] == -1 and tail["tail_padding"]


def test_tune_schedule_persists_and_auto_resolves(tp2_mesh, tmp_path,
                                                 monkeypatch):
    """The schedule autotune entry: tune_schedule times both modes,
    persists the winner under the (model, mesh, batch, cores) key, and
    MegaKernelEngine(schedule="auto") resolves to it from the cache."""
    import triton_dist_tpu.tune as tune
    from triton_dist_tpu.megakernel.engine import (
        MegaKernelEngine, lookup_schedule, tune_schedule)

    monkeypatch.setenv("TRITON_DIST_TPU_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(tune, "_CACHE", None)
    monkeypatch.setattr(tune, "_CACHE_PATH", None)

    assert lookup_schedule(CFG, tp2_mesh, batch=B) == "static"  # untuned
    winner = tune_schedule(CFG, tp2_mesh, batch=B, max_len=32,
                           tile_w=16, t_tile=16, reps=1)
    assert winner in ("static", "dynamic")
    assert lookup_schedule(CFG, tp2_mesh, batch=B) == winner
    # Cached: a second call must not re-time (hits the cache).
    assert tune_schedule(CFG, tp2_mesh, batch=B, max_len=32,
                         tile_w=16, t_tile=16, reps=1) == winner
    eng = MegaKernelEngine(CFG, tp2_mesh, batch=B, max_len=32,
                           tile_w=16, t_tile=16, schedule="auto")
    assert eng.schedule == winner


def test_megakernel_serves_real_checkpoints(tp2_mesh):
    """The dense and MoE megakernel families serve the committed
    REAL-format HF fixtures token-exactly against the layer Engine —
    checkpoint weights, not synthetic init (the reference megakernel's
    acceptance is real-model serving)."""
    import os

    from triton_dist_tpu.megakernel.engine import MegaKernelEngine
    from triton_dist_tpu.models import Engine, qwen_moe
    from triton_dist_tpu.models.hf_loader import load_hf_checkpoint

    here = os.path.dirname(os.path.abspath(__file__))
    for fixture, model in (("qwen3_tiny", None),
                           ("qwen3_moe_tiny", qwen_moe)):
        cfg, params = load_hf_checkpoint(
            os.path.join(here, "fixtures", fixture), dtype=jnp.float32)
        mk = MegaKernelEngine(cfg, tp2_mesh, batch=B, max_len=MAXLEN,
                              tile_w=16, t_tile=16, params=params,
                              keep_params=True)
        toks = np.asarray(
            mk.generate(jnp.asarray([3, 7], jnp.int32), steps=4))

        ekw = {"model": model} if model is not None else {}
        e2 = Engine(cfg, tp2_mesh, mode="xla", max_len=MAXLEN,
                    params=params, **ekw)
        ref = _layer_engine_greedy(e2, cfg,
                                   jnp.asarray([3, 7], jnp.int32), 4)
        np.testing.assert_array_equal(
            toks, ref,
            err_msg=f"megakernel vs layer engine diverged on {fixture}")


# ---------------------------------------------------------------------------
# Arena schema: the described memory layout (PR: megakernel serving
# parity) — every region named, disjoint, and addressable.
# ---------------------------------------------------------------------------

def test_arena_schema_regions_disjoint_and_named(tp2_mesh):
    """Every _alloc lands in the schema with a name + kind; the
    in-arena regions tile [0, arena_rows) exactly (no overlap, no
    gap) and the legacy offset table agrees with the schema."""
    mb = ModelBuilder(CFG, tp2_mesh, batch=B, max_len=MAXLEN,
                      tile_w=16, t_tile=16)
    mb.schema.check_disjoint()
    assert mb.schema.rows == mb.arena_rows
    for name, off in mb._offsets.items():
        assert mb.schema.region(name).offset == off
    kinds = {r.kind for r in mb.schema}
    assert {"weight", "activation", "workspace", "io"} <= kinds
    # Weight rows match the pack manifest the arena assembler uses.
    wrows = sum(r.rows for r in mb.schema.regions(kind="weight"))
    assert wrows == sum(r for _, r in mb._weight_entries)


def test_arena_schema_counter_and_buffers():
    """MoE builds name their router-counter region; engines register
    the KV pools (+ scale tables on quantized builds) as schema
    buffers, and snapshot_regions() is exactly the checkpoint set."""
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    mcfg = ModelConfig.tiny_moe(vocab_size=64, hidden_size=32,
                                num_hidden_layers=2,
                                num_attention_heads=4,
                                num_key_value_heads=2, head_dim=8,
                                num_experts=4, num_experts_per_tok=2,
                                moe_intermediate_size=32)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    eng = MegaKernelEngine(mcfg, mesh, batch=2, max_len=32, tile_w=16,
                           t_tile=16, paged=True, page=16, num_pages=5,
                           kv_dtype="int8")
    sch = eng.builder.schema
    assert "moe_counts" in sch
    assert sch.region("moe_counts").kind == "counter"
    assert sch.region("moe_counts").offset == eng.builder.moe_counts_off
    names = {r.name for r in sch.snapshot_regions()}
    assert names == {"moe_counts", "k_cache", "v_cache", "k_scale",
                     "v_scale"}
    # describe() is plain data (the docs/diagnostics surface).
    d = sch.describe()
    assert any(e["name"] == "k_scale" and e["kind"] == "scale"
               for e in d)
    # Double allocation fails loudly.
    with pytest.raises(ValueError, match="already allocated"):
        sch.alloc("moe_counts", 1, "counter")


def test_qblock_builder_schedules_verification_tasks():
    """qblock=True swaps the KV pair for WRITE_KV_QBLOCK/ATTN_QBLOCK
    (per-row-position verification tasks), requires paged, and keeps
    the dynamic claim list covering every task exactly once."""
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    mb = ModelBuilder(CFG, mesh, batch=2 * 2, max_len=32, tile_w=16,
                      t_tile=16, seq=2, qblock=True, paged=True,
                      page=16, schedule="dynamic")
    tt = set(int(t.task_type) for t in mb.graph.tasks)
    assert int(TaskType.WRITE_KV_QBLOCK) in tt
    assert int(TaskType.ATTN_QBLOCK) in tt
    assert int(TaskType.WRITE_KV) not in tt
    assert int(TaskType.ATTN_PREFILL) not in tt
    claimed = sorted(int(t) for t in mb.claims.reshape(-1) if t >= 0)
    assert claimed == list(range(len(mb.graph.tasks)))
    with pytest.raises(ValueError, match="paged"):
        ModelBuilder(CFG, mesh, batch=4, max_len=32, tile_w=16,
                     t_tile=16, seq=2, qblock=True)
