"""Megakernel: one persistent kernel per device must reproduce the
layer-by-layer decode step (reference acceptance: megakernel output vs
triton_dist layer path, ``mega_triton_kernel/test/models/``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
import jax.numpy as jnp

from triton_dist_tpu.layers import tp_attn, tp_mlp
from triton_dist_tpu.layers.norm import rms_norm
from triton_dist_tpu.megakernel import ModelBuilder, schedule
from triton_dist_tpu.megakernel.graph import Graph
from triton_dist_tpu.megakernel.task import TaskType
from triton_dist_tpu.models import dense
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.utils.testing import spmd, assert_allclose

CFG = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                       intermediate_size=32, num_hidden_layers=2,
                       num_attention_heads=4, num_key_value_heads=2,
                       head_dim=8)
B, MAXLEN, NTP = 2, 32, 2


def test_scheduler_native():
    """C++ scheduler: topological order + cycle detection."""
    s = schedule(4, [0, 1, 2], [1, 2, 3], num_cores=1)
    assert list(s["order"]) == [0, 1, 2, 3]
    with pytest.raises(ValueError, match="cycle"):
        schedule(2, [0, 1], [1, 0], num_cores=1)
    # Multi-core packing keeps deps cross-core.
    s = schedule(4, [0, 1], [2, 3], num_cores=2)
    assert sorted(s["order"]) == [0, 1, 2, 3]


def test_graph_dataflow_deps():
    g = Graph()
    t0 = g.add(TaskType.RMSNORM, (0, 0, 10, 1), reads=[(0, 2)],
               writes=[(10, 2)])
    t1 = g.add(TaskType.LINEAR, (10, 0, 20, 1, 1, 0), reads=[(10, 2)],
               writes=[(20, 2)])
    t2 = g.add(TaskType.ADD, (0, 20, 10, 1), reads=[(0, 2), (20, 2)],
               writes=[(10, 2)])  # WAR on t1's read of 10
    assert t1.deps == [t0.task_id]
    assert t0.task_id in t2.deps or t1.task_id in t2.deps


@pytest.fixture(scope="module")
def tp2_mesh():
    return Mesh(np.array(jax.devices()[:NTP]), ("tp",))


def test_megakernel_decode_vs_layers(tp2_mesh):
    mesh = tp2_mesh
    mb = ModelBuilder(CFG, mesh, batch=B, max_len=MAXLEN, tile_w=16,
                      t_tile=16)
    params = dense.init_params(jax.random.PRNGKey(0), CFG)
    specs = dense.param_specs(CFG)

    kv_loc = CFG.num_key_value_heads // NTP
    cache_shape = (CFG.num_hidden_layers, B, MAXLEN,
                   CFG.num_key_value_heads, CFG.head_dim)
    k_cache = jax.random.normal(jax.random.PRNGKey(1), cache_shape) * 0.3
    v_cache = jax.random.normal(jax.random.PRNGKey(2), cache_shape) * 0.3
    tokens = jnp.asarray([3, 17], jnp.int32)
    pos = jnp.asarray(5, jnp.int32)
    kvspec = P(None, None, None, "tp", None)

    # --- megakernel path (embedding + stack + LM head in-kernel) ---
    pack = spmd(mesh, mb.pack_arena, (specs,), P("tp", None))
    arena = pack(params)
    step = spmd(mesh, mb.step_fn(),
                (P("tp", None), kvspec, kvspec, P(None), P()),
                (P(None, "tp"), P("tp", None), kvspec, kvspec))
    logits, arena2, kc2, vc2 = step(arena, k_cache, v_cache, tokens, pos)

    # --- layer-by-layer oracle (xla mode, proven against dense) ---
    def oracle(p, tok, kc, vc):
        h = p["embed"][tok]
        new_k, new_v = kc, vc
        for li, lp in enumerate(p["layers"]):
            t = rms_norm(h, lp["ln_attn"], CFG.rms_norm_eps)
            ao, (lk, lv) = tp_attn.fwd_decode(
                lp["attn"], t, CFG, new_k[li], new_v[li], pos, mode="xla")
            new_k = new_k.at[li].set(lk)
            new_v = new_v.at[li].set(lv)
            h = h + ao
            t = rms_norm(h, lp["ln_mlp"], CFG.rms_norm_eps)
            h = h + tp_mlp.fwd(lp["mlp"], t, mode="xla_ar")
        h = rms_norm(h, p["ln_f"], CFG.rms_norm_eps)
        logits_loc = h @ p["lm_head"].T
        return (jax.lax.all_gather(logits_loc, "tp", axis=1, tiled=True),
                new_k, new_v)

    of = spmd(mesh, oracle, (specs, P(None), kvspec, kvspec),
              (P(None, None), kvspec, kvspec))
    want_logits, want_k, want_v = of(params, tokens, k_cache, v_cache)

    assert_allclose(logits, want_logits, rtol=2e-3, atol=2e-3)
    # Cache slot 5 must hold the new roped+normed K and the raw V.
    assert_allclose(np.asarray(kc2)[:, :, 5], np.asarray(want_k)[:, :, 5],
                    rtol=2e-3, atol=2e-3)
    assert_allclose(np.asarray(vc2)[:, :, 5], np.asarray(want_v)[:, :, 5],
                    rtol=2e-3, atol=2e-3)
    # Untouched slots unchanged.
    assert_allclose(np.asarray(kc2)[:, :, :5], np.asarray(k_cache)[:, :, :5])


def test_megakernel_engine_generate(tp2_mesh):
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    eng = MegaKernelEngine(CFG, tp2_mesh, batch=B, max_len=MAXLEN,
                           tile_w=16, t_tile=16, seed=4,
                           keep_params=True)
    toks = np.asarray(eng.generate(jnp.zeros((B,), jnp.int32), steps=4))
    assert toks.shape == (B, 4)
    assert np.isfinite(toks).all()

    # Oracle: same params through the layer-path Engine decode chain.
    from triton_dist_tpu.models import Engine
    import jax.numpy as jnp2
    params = jax.tree.map(np.asarray, eng.params)
    e2 = Engine(CFG, tp2_mesh, mode="xla", max_len=MAXLEN, params=params)
    # Drive the same chain manually: prefill over the single seed token
    # is equivalent to a decode at position 0 on an empty cache.
    from triton_dist_tpu.models.kv_cache import KVCache
    kv_loc = CFG.num_key_value_heads  # spec shards it; global here
    cache = KVCache.empty(CFG.num_hidden_layers, B, MAXLEN,
                          CFG.num_key_value_heads, CFG.head_dim)
    tok = jnp2.zeros((B,), jnp2.int32)
    ref = []
    for _ in range(4):
        logits, cache = e2._decode(e2.params, tok, cache)
        tok = jnp2.argmax(logits, -1).astype(jnp2.int32)
        ref.append(np.asarray(tok))
    ref = np.stack(ref, axis=1)
    np.testing.assert_array_equal(toks, ref)
