"""Hybrid GDN/full-attention model (Qwen3-Next family).

The GDN kernel's model-level contract: fused mode matches the XLA
oracle, and the recurrent-state handoff from chunked prefill into O(1)
decode reproduces the all-tokens forward (the same prefill/decode
equivalence the dense tests establish for the KV cache).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.models import Engine, qwen_next
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.dense import make_fwd_contexts
from triton_dist_tpu.utils.testing import spmd, assert_allclose

CFG = ModelConfig.tiny_next()
B, S = 2, 32


def _engine(mesh, mode):
    return Engine(CFG, mesh, mode=mode, max_len=64, seed=3,
                  block_m=8, block_n=8, block_k=32, model=qwen_next)


def _ids(seed=1, s=S):
    return jax.random.randint(jax.random.PRNGKey(seed), (B, s), 0,
                              CFG.vocab_size)


def test_layer_schedule():
    kinds, n_attn, n_gdn = qwen_next._layer_kinds(CFG)
    # interval=2 over 4 layers → gdn, attn, gdn, attn.
    assert [k for k, _ in kinds] == ["gdn", "attn", "gdn", "attn"]
    assert (n_attn, n_gdn) == (2, 2)
    assert CFG.is_hybrid


def test_forward_fused_matches_xla(tp8_mesh, tp8_ctx):
    params = qwen_next.init_params(jax.random.PRNGKey(0), CFG)
    ids = _ids()
    ctxs = make_fwd_contexts(tp8_ctx, "tp", block_m=8, block_n=8,
                             block_k=32)

    def run(mode):
        return spmd(
            tp8_mesh,
            lambda p, i: qwen_next.forward_tokens(p, i, CFG, mode=mode,
                                                  ctxs=ctxs),
            (qwen_next.param_specs(CFG), P(None, None)),
            P(None, None, None))(params, ids)

    logits_xla = run("xla")
    assert logits_xla.shape == (B, S, CFG.vocab_size)
    assert_allclose(run("fused"), logits_xla, rtol=2e-3, atol=2e-3)


def test_prefill_decode_matches_forward(tp8_mesh, tp8_ctx):
    """Greedy continuation from (prefill → decode chain) must equal the
    all-tokens forward teacher-forced on the same tokens — proving the
    GDN recurrent state and the KV cache carry exactly the prefix
    information."""
    eng = _engine(tp8_mesh, "xla")
    ids = _ids(seed=2, s=16)
    gen = 4
    chain = np.asarray(eng.serve(ids, gen_len=gen))        # (B, gen)

    full = jnp.concatenate([ids, jnp.asarray(chain)], axis=1)
    ctxs = make_fwd_contexts(tp8_ctx, "tp", block_m=8, block_n=8,
                             block_k=32)
    fwd = spmd(tp8_mesh,
               lambda p, i: qwen_next.forward_tokens(p, i, CFG,
                                                     ctxs=ctxs),
               (qwen_next.param_specs(CFG), P(None, None)),
               P(None, None, None))(
        jax.tree.map(np.asarray, eng.params), full)
    want = np.asarray(jnp.argmax(fwd, -1))[:, 15:15 + gen]
    np.testing.assert_array_equal(chain, want)


def test_decode_fused_matches_xla(tp8_mesh):
    ids = _ids(seed=3, s=16)
    toks_xla = np.asarray(_engine(tp8_mesh, "xla").serve(ids, gen_len=4))
    toks_fused = np.asarray(
        _engine(tp8_mesh, "fused").serve(ids, gen_len=4))
    np.testing.assert_array_equal(toks_fused, toks_xla)
    assert toks_xla.shape == (B, 4)


MOE_CFG = ModelConfig.tiny_next(num_experts=8, num_experts_per_tok=2,
                                moe_intermediate_size=32)


def test_moe_ffn_forward_fused_matches_xla(tp8_mesh, tp8_ctx):
    """MoE hybrid configs must actually run the MoE FFN (r2 advisor:
    cfg.is_moe was silently ignored) and the fused pipeline must match
    the XLA oracle."""
    params = qwen_next.init_params(jax.random.PRNGKey(7), MOE_CFG)
    # MoE param set, not a dense MLP: router + per-expert weights.
    assert "router" in params["layers"][0]["mlp"]
    assert params["layers"][0]["mlp"]["w_gate"].shape[0] == 8
    ids = _ids(seed=8)
    ctxs = make_fwd_contexts(tp8_ctx, "tp", block_m=8, block_n=8,
                             block_k=32)

    def run(mode):
        return spmd(
            tp8_mesh,
            lambda p, i: qwen_next.forward_tokens(p, i, MOE_CFG,
                                                  mode=mode, ctxs=ctxs),
            (qwen_next.param_specs(MOE_CFG), P(None, None)),
            P(None, None, None))(params, ids)

    logits_xla = run("xla")
    assert logits_xla.shape == (B, S, MOE_CFG.vocab_size)
    assert_allclose(run("fused"), logits_xla, rtol=2e-3, atol=2e-3)


def test_moe_prefill_decode_matches_forward(tp8_mesh, tp8_ctx):
    """The MoE FFN decode path (replicated rows + AR) must agree with
    the token-sharded prefill path token-for-token."""
    eng = Engine(MOE_CFG, tp8_mesh, mode="xla", max_len=64, seed=9,
                 block_m=8, block_n=8, block_k=32, model=qwen_next)
    ids = _ids(seed=10, s=16)
    gen = 4
    chain = np.asarray(eng.serve(ids, gen_len=gen))

    full = jnp.concatenate([ids, jnp.asarray(chain)], axis=1)
    ctxs = make_fwd_contexts(tp8_ctx, "tp", block_m=8, block_n=8,
                             block_k=32)
    fwd = spmd(tp8_mesh,
               lambda p, i: qwen_next.forward_tokens(p, i, MOE_CFG,
                                                     ctxs=ctxs),
               (qwen_next.param_specs(MOE_CFG), P(None, None)),
               P(None, None, None))(
        jax.tree.map(np.asarray, eng.params), full)
    want = np.asarray(jnp.argmax(fwd, -1))[:, 15:15 + gen]
    np.testing.assert_array_equal(chain, want)


def test_state_is_constant_memory(tp8_mesh, tp8_ctx):
    """The GDN cache does not grow with sequence length (the point of
    the hybrid architecture for long context)."""
    eng = _engine(tp8_mesh, "xla")
    _, c16 = eng.prefill(_ids(seed=4, s=16))
    _, c32 = eng.prefill(_ids(seed=5, s=32))
    assert c16.states.shape == c32.states.shape


def test_hybrid_training_step(tp8_mesh):
    """Grads flow through the whole hybrid stack — chunked delta rule
    (triangular solve), conv, gates — and one SGD step lowers the loss.
    The hybrid family is trainable, not inference-only (long-context
    training is the architecture's point)."""
    import dataclasses

    cfg = dataclasses.replace(
        ModelConfig.tiny_next(), gdn_num_key_heads=8, gdn_conv_kernel=4,
        attn_gate=True, partial_rotary_factor=0.5)
    params = qwen_next.init_params(jax.random.PRNGKey(0), cfg)
    specs = qwen_next.param_specs(cfg)
    ids = _ids(seed=5, s=16)

    def loss_fn(p, i):
        logits = qwen_next.forward_tokens(p, i, cfg)
        tgt = jnp.roll(i, -1, axis=1)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)
        return jnp.mean(nll)

    def train_step(p, i):
        loss, grads = jax.value_and_grad(loss_fn)(p, i)

        def has_tp(spec):
            return any(e == "tp" or (isinstance(e, tuple) and "tp" in e)
                       for e in tuple(spec))

        # Every shard computes the FULL loss from the replicated
        # logits, so backward counts each parameter's contribution
        # axis_size times in aggregate: complete replicated-spec leaves
        # with a psum (their per-shard grad saw only this rank's token
        # slice), then scale EVERYTHING by 1/n to recover the true
        # gradient (verified against a single-device oracle).
        n = jax.lax.axis_size("tp")
        grads = jax.tree.map(
            lambda g, s: (g if has_tp(s)
                          else jax.lax.psum(g, "tp")) / n,
            grads, specs)
        new_p = jax.tree.map(lambda w, g: w - 1e-2 * g, p, grads)
        return loss, new_p

    step = spmd(tp8_mesh, train_step, (specs, P(None, None)),
                (P(), specs))
    loss0, p1 = step(params, ids)
    assert np.isfinite(float(loss0))
    flat, _ = jax.tree_util.tree_flatten(p1)
    assert all(bool(jnp.isfinite(x).all()) for x in flat)
    loss1, _ = step(jax.tree.map(np.asarray, p1), ids)
    assert float(loss1) < float(loss0), (float(loss0), float(loss1))
