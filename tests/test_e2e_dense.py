"""End-to-end dense model tests (reference: ``test_tp_e2e.py --check``
pattern — triton_dist forward vs torch-eager oracle,
``docs/getting-started/e2e/e2e_dense.md:115-124``).

Here the oracle is the same model in mode="xla" (pure lax collectives);
mode="fused" must match, and a 1-device dense run must match both.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.models import ModelConfig, Engine
from triton_dist_tpu.utils.testing import assert_allclose

CFG = ModelConfig.tiny()
B, S = 2, 32


def _engine(mesh, mode, **kw):
    return Engine(CFG, mesh, mode=mode, max_len=64, seed=3,
                  block_m=8, block_n=8, block_k=32, **kw)


@pytest.fixture(scope="module")
def ids():
    return jax.random.randint(jax.random.PRNGKey(7), (B, S), 0,
                              CFG.vocab_size)


def test_prefill_fused_matches_xla(tp8_mesh, ids):
    e_xla = _engine(tp8_mesh, "xla")
    e_fused = _engine(tp8_mesh, "fused")
    logits_xla, cache_xla = e_xla.prefill(ids)
    logits_fused, cache_fused = e_fused.prefill(ids)
    assert_allclose(logits_fused, logits_xla, rtol=2e-3, atol=2e-3)
    assert_allclose(cache_fused.k, cache_xla.k, rtol=2e-3, atol=2e-3)


def test_decode_fused_matches_xla(tp8_mesh, ids):
    e_xla = _engine(tp8_mesh, "xla")
    e_fused = _engine(tp8_mesh, "fused")
    toks_xla = np.asarray(e_xla.serve(ids, gen_len=4))
    toks_fused = np.asarray(e_fused.serve(ids, gen_len=4))
    np.testing.assert_array_equal(toks_fused, toks_xla)
    assert toks_xla.shape == (B, 4)


def test_cache_length_advances(tp8_mesh, ids):
    e = _engine(tp8_mesh, "xla")
    logits, cache = e.prefill(ids)
    assert int(np.asarray(cache.length)) == S
    tok = jnp.argmax(logits, -1).astype(jnp.int32)
    _, cache2 = e.decode(tok, cache)
    assert int(np.asarray(cache2.length)) == S + 1


def test_serve_sampling(tp8_mesh, ids):
    """Sampling decode: deterministic per seed, different across seeds,
    and temperature→0 converges to greedy. top_k=1 IS greedy."""
    eng = _engine(tp8_mesh, "xla")
    greedy = np.asarray(eng.serve(ids, gen_len=4))

    s1 = np.asarray(eng.serve(ids, gen_len=4, temperature=0.8, seed=1))
    s1b = np.asarray(eng.serve(ids, gen_len=4, temperature=0.8, seed=1))
    np.testing.assert_array_equal(s1, s1b)       # same seed → same tokens

    s2 = np.asarray(eng.serve(ids, gen_len=4, temperature=5.0, seed=2))
    assert s1.shape == s2.shape == greedy.shape

    k1 = np.asarray(eng.serve(ids, gen_len=4, temperature=0.8,
                              top_k=1, seed=9))
    np.testing.assert_array_equal(k1, greedy)    # top-1 == argmax


def test_engine_rejects_moe_impl_on_dense_model(tp8_mesh):
    """Engine(moe_impl=...) with a non-MoE model raises a clear error
    instead of a TypeError inside param_specs (ADVICE r4)."""
    import pytest
    from triton_dist_tpu.models import Engine, ModelConfig

    with pytest.raises(ValueError, match="not a MoE model"):
        Engine(ModelConfig.tiny(), tp8_mesh, moe_impl="ep")
