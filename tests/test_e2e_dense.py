"""End-to-end dense model tests (reference: ``test_tp_e2e.py --check``
pattern — triton_dist forward vs torch-eager oracle,
``docs/getting-started/e2e/e2e_dense.md:115-124``).

Here the oracle is the same model in mode="xla" (pure lax collectives);
mode="fused" must match, and a 1-device dense run must match both.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.models import ModelConfig, Engine
from triton_dist_tpu.utils.testing import assert_allclose

CFG = ModelConfig.tiny()
B, S = 2, 32


def _engine(mesh, mode, **kw):
    return Engine(CFG, mesh, mode=mode, max_len=64, seed=3,
                  block_m=8, block_n=8, block_k=32, **kw)


@pytest.fixture(scope="module")
def ids():
    return jax.random.randint(jax.random.PRNGKey(7), (B, S), 0,
                              CFG.vocab_size)


def test_prefill_fused_matches_xla(tp8_mesh, ids):
    e_xla = _engine(tp8_mesh, "xla")
    e_fused = _engine(tp8_mesh, "fused")
    logits_xla, cache_xla = e_xla.prefill(ids)
    logits_fused, cache_fused = e_fused.prefill(ids)
    assert_allclose(logits_fused, logits_xla, rtol=2e-3, atol=2e-3)
    assert_allclose(cache_fused.k, cache_xla.k, rtol=2e-3, atol=2e-3)


def test_decode_fused_matches_xla(tp8_mesh, ids):
    e_xla = _engine(tp8_mesh, "xla")
    e_fused = _engine(tp8_mesh, "fused")
    toks_xla = np.asarray(e_xla.serve(ids, gen_len=4))
    toks_fused = np.asarray(e_fused.serve(ids, gen_len=4))
    np.testing.assert_array_equal(toks_fused, toks_xla)
    assert toks_xla.shape == (B, 4)


def test_cache_length_advances(tp8_mesh, ids):
    e = _engine(tp8_mesh, "xla")
    logits, cache = e.prefill(ids)
    assert int(np.asarray(cache.length)) == S
    tok = jnp.argmax(logits, -1).astype(jnp.int32)
    _, cache2 = e.decode(tok, cache)
    assert int(np.asarray(cache2.length)) == S + 1


def test_serve_sampling(tp8_mesh, ids):
    """Sampling decode: deterministic per seed, different across seeds,
    and temperature→0 converges to greedy. top_k=1 IS greedy."""
    eng = _engine(tp8_mesh, "xla")
    greedy = np.asarray(eng.serve(ids, gen_len=4))

    s1 = np.asarray(eng.serve(ids, gen_len=4, temperature=0.8, seed=1))
    s1b = np.asarray(eng.serve(ids, gen_len=4, temperature=0.8, seed=1))
    np.testing.assert_array_equal(s1, s1b)       # same seed → same tokens

    s2 = np.asarray(eng.serve(ids, gen_len=4, temperature=5.0, seed=2))
    assert s1.shape == s2.shape == greedy.shape

    k1 = np.asarray(eng.serve(ids, gen_len=4, temperature=0.8,
                              top_k=1, seed=9))
    np.testing.assert_array_equal(k1, greedy)    # top-1 == argmax


def test_engine_rejects_moe_impl_on_dense_model(tp8_mesh):
    """Engine(moe_impl=...) with a non-MoE model raises a clear error
    instead of a TypeError inside param_specs (ADVICE r4)."""
    import pytest
    from triton_dist_tpu.models import Engine, ModelConfig

    with pytest.raises(ValueError, match="not a MoE model"):
        Engine(ModelConfig.tiny(), tp8_mesh, moe_impl="ep")


def test_dense_attention_bias_seed_oss_shape(tp8_mesh, tp8_ctx):
    """Seed-OSS-class dense models (attention biases, NO per-head q/k
    norm — reference serves ByteDance-Seed/Seed-OSS-36B-Instruct
    through the same DenseLLM, models/__init__.py:42): fused modes must
    match the XLA path with biases active."""
    import dataclasses

    from triton_dist_tpu.models import ModelConfig, Engine

    cfg = dataclasses.replace(ModelConfig.tiny(), attention_bias=True,
                              qk_norm=False,
                              model_name="seed-oss-tiny")
    ids = jax.random.randint(jax.random.PRNGKey(0), (2, 8), 0,
                             cfg.vocab_size)

    # Nonzero biases so the test actually exercises them.
    from triton_dist_tpu.models import dense as dense_mod
    params = dense_mod.init_params(jax.random.PRNGKey(1), cfg)
    for lyr in params["layers"]:
        assert "bq" in lyr["attn"] and "q_norm" not in lyr["attn"]
        lyr["attn"]["bq"] = jnp.full_like(lyr["attn"]["bq"], 0.05)
        lyr["attn"]["bo"] = jnp.full_like(lyr["attn"]["bo"], -0.03)

    # Biases must be load-bearing: the per-shard forward with nonzero
    # bq/bo differs from the zero-bias forward at the LOGITS level.
    from jax.sharding import PartitionSpec as P
    from triton_dist_tpu.utils.testing import spmd
    specs = dense_mod.param_specs(cfg)
    params0 = dense_mod.init_params(jax.random.PRNGKey(1), cfg)
    f = spmd(tp8_mesh,
             lambda p, i: dense_mod.prefill(p, i, cfg, max_len=16)[0],
             (specs, P(None, None)), P(None, None))
    lg_b = np.asarray(f(params, ids))
    lg_0 = np.asarray(f(params0, ids))
    assert np.abs(lg_b - lg_0).max() > 1e-4

    outs = {}
    for mode in ("xla", "fused"):
        eng = Engine(cfg, tp8_mesh, mode=mode, params=params)
        outs[mode] = np.asarray(eng.serve(ids, gen_len=4))
    np.testing.assert_array_equal(outs["xla"], outs["fused"])


def test_hf_loader_maps_bias_checkpoint():
    """State-dict mapping for a bias-carrying, norm-free checkpoint."""
    import numpy as _np
    from triton_dist_tpu.models.hf_loader import params_from_hf_state_dict
    from triton_dist_tpu.models import ModelConfig
    import dataclasses

    cfg = dataclasses.replace(
        ModelConfig.tiny(vocab_size=32, hidden_size=16,
                         intermediate_size=32, num_hidden_layers=1,
                         num_attention_heads=2, num_key_value_heads=2,
                         head_dim=8),
        attention_bias=True, qk_norm=False)
    d, hq, hkv = 16, 16, 16
    state = {}
    p = "model.layers.0."
    rng = _np.random.default_rng(0)
    for k, shape in [
            (p + "self_attn.q_proj.weight", (hq, d)),
            (p + "self_attn.k_proj.weight", (hkv, d)),
            (p + "self_attn.v_proj.weight", (hkv, d)),
            (p + "self_attn.o_proj.weight", (d, hq)),
            (p + "self_attn.q_proj.bias", (hq,)),
            (p + "self_attn.k_proj.bias", (hkv,)),
            (p + "self_attn.v_proj.bias", (hkv,)),
            (p + "mlp.gate_proj.weight", (32, d)),
            (p + "mlp.up_proj.weight", (32, d)),
            (p + "mlp.down_proj.weight", (d, 32)),
            (p + "input_layernorm.weight", (d,)),
            (p + "post_attention_layernorm.weight", (d,)),
            ("model.embed_tokens.weight", (32, d)),
            ("model.norm.weight", (d,)),
            ("lm_head.weight", (32, d)),
    ]:
        state[k] = rng.standard_normal(shape).astype(_np.float32)
    params = params_from_hf_state_dict(state, cfg)
    attn = params["layers"][0]["attn"]
    assert "bq" in attn and "bo" in attn and "q_norm" not in attn
    np.testing.assert_allclose(
        np.asarray(attn["bq"], np.float32),
        state[p + "self_attn.q_proj.bias"], rtol=1e-2, atol=1e-2)
    # o_proj.bias absent -> zeros fallback.
    assert np.all(np.asarray(attn["bo"], np.float32) == 0.0)
