"""Persistent autotune-cache coverage (``triton_dist_tpu/tune.py``):
round-trip, dependency-stamp invalidation, and concurrent writers
leaving one valid JSON file (the ISSUE-2 satellite)."""

import json
import os
import threading

import pytest

from triton_dist_tpu import tune


@pytest.fixture
def fresh_cache(tmp_path, monkeypatch):
    """Redirect the tune cache into a private tmp dir and reset the
    module's memoized path + in-memory cache around the test."""
    monkeypatch.setenv("TRITON_DIST_TPU_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(tune, "_CACHE_PATH", None)
    monkeypatch.setattr(tune, "_CACHE", None)
    yield tmp_path
    tune._CACHE_PATH = None
    tune._CACHE = None


def test_round_trip(fresh_cache):
    key = tune.make_key("some_op", m=128, k=64, n=32, dtype="bfloat16")
    cfg = {"block_m": 256, "swizzle_mode": "ag", "prefetch_depth": 2}
    assert tune.load_autotune_data(key) is None
    tune.store_autotune_data(key, cfg, seconds=1.5e-3)
    assert tune.load_autotune_data(key) == cfg
    # A fresh process (cleared memo) reads the same winner from disk.
    tune._CACHE = None
    assert tune.load_autotune_data(key) == cfg
    rec = json.load(open(tune.cache_path()))[key]
    assert rec["seconds"] == pytest.approx(1.5e-3)
    assert rec["versions"] == tune._dep_versions()


def test_make_key_stable_and_distinct(fresh_cache):
    k1 = tune.make_key("op", m=128, n=64)
    assert k1 == tune.make_key("op", n=64, m=128)   # order-insensitive
    assert k1 != tune.make_key("op", m=128, n=65)
    assert k1 != tune.make_key("op2", m=128, n=64)
    assert k1.startswith("op:")


def test_dep_stamp_invalidation(fresh_cache, monkeypatch):
    """A winner tuned under a different stack (jax version, backend)
    must read as a miss, not a hit."""
    key = tune.make_key("op", m=8)
    tune.store_autotune_data(key, {"block_m": 64})
    assert tune.load_autotune_data(key) == {"block_m": 64}
    monkeypatch.setattr(
        tune, "_dep_versions",
        lambda: {"jax": "999.0", "triton_dist_tpu": "x", "backend": "tpu"})
    assert tune.load_autotune_data(key) is None


def test_corrupt_cache_file_is_a_miss(fresh_cache):
    with open(tune.cache_path(), "w") as f:
        f.write("{ not json")
    assert tune.load_autotune_data(tune.make_key("op")) is None
    # And storing over the corrupt file heals it.
    key = tune.make_key("op", m=1)
    tune.store_autotune_data(key, {"block_m": 8})
    assert tune.load_autotune_data(key) == {"block_m": 8}


def test_concurrent_writers_leave_valid_json(fresh_cache):
    """Threaded store_autotune_data from many writers: the final file
    must be one complete JSON document containing every key (the _LOCK
    serializes in-process writers; the private-temp-file + os.replace
    protocol keeps any reader off half-written bytes)."""
    n_threads, n_writes = 8, 10
    errors = []

    def writer(tid):
        try:
            for i in range(n_writes):
                key = tune.make_key("op", thread=tid, i=i)
                tune.store_autotune_data(key, {"block_m": 8 * (i + 1)},
                                         seconds=float(i))
        except Exception as e:   # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    data = json.load(open(tune.cache_path()))   # parses = not corrupt
    assert len(data) == n_threads * n_writes
    tune._CACHE = None                          # force re-read from disk
    for tid in range(n_threads):
        for i in range(n_writes):
            key = tune.make_key("op", thread=tid, i=i)
            assert tune.load_autotune_data(key) == {"block_m": 8 * (i + 1)}
    # No leftover temp files from any writer.
    leftovers = [p for p in os.listdir(fresh_cache) if p.endswith(".tmp")]
    assert leftovers == []


def test_clear_cache(fresh_cache):
    key = tune.make_key("op", m=2)
    tune.store_autotune_data(key, {"block_m": 16})
    tune.clear_cache()
    assert tune.load_autotune_data(key) is None
    assert not os.path.exists(tune.cache_path())


def test_mesh_key():
    class FakeMesh:
        axes = ("tp", "dp")
        sizes = (8, 2)

    assert tune.mesh_key(FakeMesh()) == "tp8xdp2"
