"""Resilience battery: fault plans against the comm path, watchdog
deadlines, and the graceful-degradation policy.

Acceptance contract (ISSUE 1): every injected fault plan TERMINATES —
either bit-correct output (tolerated fault) or a structured
``CommTimeoutError`` carrying rank + op + progress (detected fault) —
never a hang. Deadlock-prone plans run through the subprocess harness
(``resilience.harness``), whose deadline is the no-hang guarantee.

On the old generic discharge interpreter (``compat.degraded(
"tpu_interpret_mode")``) semaphore waits do not block, so plans that
deadlock the real protocol degrade to tolerated faults there; the
assertions accept both verdicts of the contract, and the subprocess
deadline still bounds every case.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.ops.ag_gemm import (
    ag_gemm, ag_gemm_ref, create_ag_gemm_context)
from triton_dist_tpu.resilience import (
    CommTimeoutError, InjectedFault, Watchdog, faults, harness, policy,
)
from triton_dist_tpu.utils import compat
from triton_dist_tpu.utils.testing import assert_allclose, spmd

# Bound for subprocess cases: covers jax import + trace in the child
# with margin; the deadline only has to FIRE for genuinely wedged
# schedules (blocking interpreter backends).
SUBPROC_DEADLINE_S = 240.0


def _run_ag_gemm(mesh, ctx8, plan=None):
    """Trace a FRESH ag_gemm closure (inside the inject scope when a
    plan is given — faults bake in at trace time) and return its
    output; never reuses a jit cache across plans."""
    n, m_loc, kdim, nloc = 8, 16, 128, 128
    a = (jnp.arange(n * m_loc * kdim, dtype=jnp.float32)
         .reshape(n * m_loc, kdim) % 13) / 13.0
    b = (jnp.arange(kdim * nloc, dtype=jnp.float32)
         .reshape(kdim, nloc) % 7) / 7.0
    ctx = create_ag_gemm_context(ctx8, "tp", block_m=m_loc,
                                 block_n=nloc, block_k=kdim)

    def call():
        f = spmd(mesh, lambda a_, b_: ag_gemm(a_, b_, ctx),
                 (P("tp", None), P(None, None)), P(None, None))
        return f(a, b)

    if plan is None:
        out = call()
    else:
        with faults.inject(plan):
            out = call()
    want = spmd(mesh, lambda a_, b_: ag_gemm_ref(a_, b_, axis="tp"),
                (P("tp", None), P(None, None)), P(None, None))(a, b)
    return out, want


# ---------------------------------------------------------------------------
# Tolerated faults: adversarial timing the protocols must absorb.
# ---------------------------------------------------------------------------

def test_delayed_dma_ag_gemm_bit_correct(tp8_mesh, tp8_ctx):
    """Maximally-late DMA completion + a spin before rank 2's ring
    kick-off put: the arrival waits must still certify every chunk."""
    plan = faults.get_plan("delayed_dma", op="ag_gemm", rank=2, k=0,
                           iters=5000)
    out, want = _run_ag_gemm(tp8_mesh, tp8_ctx, plan)
    assert_allclose(out, want, rtol=1e-4, atol=1e-4)


def test_skewed_barrier_ag_gemm_bit_correct(tp8_mesh, tp8_ctx):
    """One rank arrives late at the entry barrier (straggler spin):
    the reference's straggler_option scenario, as a named plan."""
    plan = faults.get_plan("skewed_barrier", op="ag_gemm", rank=5,
                           iters=5000)
    out, want = _run_ag_gemm(tp8_mesh, tp8_ctx, plan)
    assert_allclose(out, want, rtol=1e-4, atol=1e-4)


def test_no_plan_is_free_and_correct(tp8_mesh, tp8_ctx):
    """The hooks are inert without an active plan."""
    assert faults.active_plan() is None
    out, want = _run_ag_gemm(tp8_mesh, tp8_ctx, None)
    assert_allclose(out, want, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# Detected faults: protocol-breaking plans must terminate in a bounded,
# attributable way. Subprocess-isolated: a genuinely wedged interpreter
# thread cannot be cancelled in-process.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("plan", ["dropped_signal", "dup_signal"])
def test_signal_faults_ag_gemm_terminate(plan):
    try:
        verdict, _ = harness.run_plan(plan, "ag_gemm", rank=1, k=0,
                                      deadline_s=SUBPROC_DEADLINE_S)
    except CommTimeoutError as e:
        # Detected: the structured error must attribute the hang.
        assert e.op == "ag_gemm"
        assert e.timeout_s == SUBPROC_DEADLINE_S
        assert e.progress is not None, "no progress marker recorded"
        return
    assert verdict == "ok"   # tolerated: bit-correct output


@pytest.mark.slow
@pytest.mark.skipif(
    compat.degraded("tpu_interpret_mode"),
    reason="megakernel needs the thread-per-device interpreter (the "
           "discharge simulator rejects its dynamic-size DMA "
           "transforms)")
def test_dropped_edge_megakernel_terminates():
    """A suppressed scoreboard completion signal either leaves the
    merged queue's output intact (non-blocking backend) or wedges the
    schedule — which must surface as CommTimeoutError naming the
    last-completed queue slot, not as a hang."""
    try:
        verdict, _ = harness.run_plan(
            "dropped_edge", "megakernel", k=0,
            deadline_s=SUBPROC_DEADLINE_S,
            extra_env={"TRITON_DIST_TPU_TRACE_PROGRESS": "1"})
    except CommTimeoutError as e:
        assert e.op == "megakernel"
        assert e.progress is not None
        return
    assert verdict == "ok"


def test_fail_kth_call_raises_structured():
    plan = faults.get_plan("fail_kth_call", op="ag_gemm", k=1)
    with faults.inject(plan):
        with faults.on_op_call("ag_gemm"):
            pass                      # call 0 passes
        with pytest.raises(InjectedFault) as ei:
            with faults.on_op_call("ag_gemm"):
                pass                  # call 1 raises
    assert ei.value.op == "ag_gemm"
    assert ei.value.call_index == 1
    # Other ops are untouched.
    with faults.inject(plan):
        with faults.on_op_call("gemm_rs"):
            pass


# ---------------------------------------------------------------------------
# Watchdog semantics.
# ---------------------------------------------------------------------------

def test_watchdog_timeout_structured():
    import time

    wd = Watchdog(0.2, op="unit.slow",
                  progress_fn=lambda: {"step": 7})
    with pytest.raises(CommTimeoutError) as ei:
        wd.run(time.sleep, 5.0)
    e = ei.value
    assert e.op == "unit.slow"
    assert e.timeout_s == 0.2
    assert e.progress == {"step": 7}
    assert e.rank == jax.process_index()
    for field in ("unit.slow", "progress"):
        assert field in str(e)


def test_watchdog_passthrough_and_errors():
    wd = Watchdog(5.0, op="unit.fast")
    assert wd.run(lambda: 42) == 42

    with pytest.raises(ZeroDivisionError):
        wd.run(lambda: 1 // 0)


def test_shmem_barrier_cached_and_bounded(tp8_mesh):
    from triton_dist_tpu.shmem import workspace

    workspace._BARRIER_CACHE.clear()
    workspace.barrier_all(tp8_mesh, timeout_s=60.0)
    assert len(workspace._BARRIER_CACHE) == 1
    compiled = workspace._BARRIER_CACHE[(tp8_mesh, "tp")]
    workspace.barrier_all(tp8_mesh)           # satellite: no re-jit
    assert workspace._BARRIER_CACHE[(tp8_mesh, "tp")] is compiled
    assert len(workspace._BARRIER_CACHE) == 1


# ---------------------------------------------------------------------------
# Bring-up / teardown robustness (satellites).
# ---------------------------------------------------------------------------

def test_initialize_retries_with_backoff(monkeypatch):
    from triton_dist_tpu.utils import distributed

    calls = []
    sleeps = []

    def flaky(**kw):
        calls.append(kw)
        if len(calls) < 3:
            raise RuntimeError("coordinator not ready")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    monkeypatch.setattr(distributed.time, "sleep", sleeps.append)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        distributed.initialize_distributed(
            coordinator_address="localhost:1234", num_processes=2,
            process_id=0, max_attempts=4, backoff_s=0.25)
    assert len(calls) == 3                      # 2 failures + 1 success
    assert sleeps == [0.25, 0.5]                # exponential backoff


def test_initialize_exhausts_attempts(monkeypatch):
    from triton_dist_tpu.utils import distributed

    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda **kw: (_ for _ in ()).throw(RuntimeError("nope")))
    monkeypatch.setattr(distributed.time, "sleep", lambda s: None)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(RuntimeError, match="after 2 attempts"):
            distributed.initialize_distributed(
                coordinator_address="localhost:1234", num_processes=2,
                process_id=0, max_attempts=2)


def test_finalize_warns_on_teardown_failure(monkeypatch):
    from triton_dist_tpu.utils import distributed

    monkeypatch.setattr(
        jax.distributed, "shutdown",
        lambda: (_ for _ in ()).throw(RuntimeError("dead coordinator")))
    with pytest.warns(RuntimeWarning, match="dead coordinator"):
        distributed.finalize_distributed()


# ---------------------------------------------------------------------------
# Graceful degradation: Engine fallback="xla".
# ---------------------------------------------------------------------------

# Head counts divisible by the 8-way tp mesh the engine tests run on.
CFG = ModelConfig.tiny(vocab_size=64, hidden_size=64,
                       intermediate_size=64, num_hidden_layers=2,
                       num_attention_heads=8, num_key_value_heads=8,
                       head_dim=8)


def test_engine_fallback_serves_when_fused_fails(tp8_mesh):
    """Force every fused op call to raise: Engine(fallback="xla") must
    log once, rebuild on the XLA path, and serve the same tokens the
    plain-XLA engine serves."""
    from triton_dist_tpu.models.engine import Engine

    policy.reset()
    ids = np.arange(2 * 4, dtype=np.int32).reshape(2, 4) % 7

    want = Engine(CFG, tp8_mesh, mode="xla", max_len=32,
                  seed=3).serve(ids, gen_len=4)

    plan = faults.get_plan("fail_kth_call", op="*", k=0)
    with faults.inject(plan):
        eng = Engine(CFG, tp8_mesh, mode="fused", max_len=32, seed=3,
                     fallback="xla")
        got = eng.serve(ids, gen_len=4)
    assert eng.mode == "xla"          # degraded, not dead
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    policy.reset()


def test_engine_no_fallback_raises(tp8_mesh):
    from triton_dist_tpu.models.engine import Engine

    policy.reset()
    plan = faults.get_plan("fail_kth_call", op="*", k=0)
    ids = np.zeros((2, 4), np.int32)
    with faults.inject(plan):
        eng = Engine(CFG, tp8_mesh, mode="fused", max_len=32)
        with pytest.raises(Exception):
            eng.serve(ids, gen_len=2)
    policy.reset()


def test_decode_counter_not_advanced_on_failure(tp8_mesh):
    """Satellite: a raised decode step must leave the overflow guard
    exactly where it was."""
    from triton_dist_tpu.models.engine import Engine

    eng = Engine(CFG, tp8_mesh, mode="xla", max_len=32)
    logits, cache = eng.prefill(np.zeros((2, 4), np.int32))
    assert eng._host_len == 4

    def boom(*a, **k):
        raise RuntimeError("injected decode failure")

    real = eng._decode
    eng._decode = boom
    with pytest.raises(RuntimeError, match="injected decode failure"):
        eng.decode(np.zeros((2,), np.int32), cache)
    assert eng._host_len == 4         # unchanged after the raise
    eng._decode = real
    logits, cache = eng.decode(np.zeros((2,), np.int32), cache)
    assert eng._host_len == 5


def test_policy_force_env(monkeypatch):
    policy.reset()
    monkeypatch.setenv("TRITON_DIST_TPU_FORCE_XLA", "gemm_rs")
    assert policy.should_fallback("gemm_rs")
    monkeypatch.setenv("TRITON_DIST_TPU_FORCE_XLA", "*")
    assert policy.should_fallback("anything")
    monkeypatch.delenv("TRITON_DIST_TPU_FORCE_XLA")
    policy.reset()


def test_policy_note_failure_sticky():
    policy.reset()
    assert not policy.should_fallback("unit_op")
    policy.note_failure("unit_op", RuntimeError("boom"))
    assert policy.should_fallback("unit_op")
    policy.reset()
    assert not policy.should_fallback("unit_op")


def test_force_xla_reroutes_op_dispatch(tp8_mesh, tp8_ctx, monkeypatch):
    """TRITON_DIST_TPU_FORCE_XLA must actually change the dispatch:
    with the fused impl patched to raise, the op only survives if the
    wrapper re-routed through the XLA oracle — and the output must
    still be correct."""
    import importlib

    # ops/__init__ re-exports the functions under the module names, so
    # attribute-style imports resolve to the functions; go via
    # sys.modules for the module objects.
    ag_mod = importlib.import_module("triton_dist_tpu.ops.ag_gemm")
    a2a_mod = importlib.import_module("triton_dist_tpu.ops.all_to_all")
    rs_mod = importlib.import_module("triton_dist_tpu.ops.gemm_rs")

    policy.reset()
    monkeypatch.setenv("TRITON_DIST_TPU_FORCE_XLA",
                       "ag_gemm,gemm_rs,all_to_all")

    def forbidden(*a, **k):
        raise AssertionError("fused impl dispatched despite FORCE_XLA")

    monkeypatch.setattr(ag_mod, "_ag_gemm_impl", forbidden)
    monkeypatch.setattr(rs_mod, "_gemm_rs_impl", forbidden)
    monkeypatch.setattr(a2a_mod, "_all_to_all_impl", forbidden)

    out, want = _run_ag_gemm(tp8_mesh, tp8_ctx)
    assert_allclose(out, want, rtol=1e-4, atol=1e-4)

    a = (jnp.arange(8 * 16 * 128, dtype=jnp.float32)
         .reshape(8 * 16, 128) % 11) / 11.0
    b = (jnp.arange(128 * 128, dtype=jnp.float32)
         .reshape(128, 128) % 5) / 5.0
    ctx = rs_mod.create_gemm_rs_context(tp8_ctx, "tp")
    got = spmd(tp8_mesh, lambda a_, b_: rs_mod.gemm_rs(a_, b_, ctx),
               (P(None, "tp"), P("tp", None)), P("tp", None))(a, b)
    ref = spmd(tp8_mesh,
               lambda a_, b_: rs_mod.gemm_rs_ref(a_, b_, axis="tp"),
               (P(None, "tp"), P("tp", None)), P("tp", None))(a, b)
    assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    x = jnp.arange(8 * 8 * 4, dtype=jnp.float32).reshape(8 * 8, 4)
    got = spmd(tp8_mesh,
               lambda x_: a2a_mod.all_to_all(x_, ctx=tp8_ctx, axis="tp"),
               P("tp", None), P("tp", None))(x)
    ref = spmd(tp8_mesh,
               lambda x_: a2a_mod.all_to_all_ref(x_, axis="tp"),
               P("tp", None), P("tp", None))(x)
    assert_allclose(got, ref, rtol=0, atol=0)
    policy.reset()


def test_health_probe_reports_healthy(tp8_mesh):
    """On any working interpret backend the tiny fused canary matches
    its oracle — the probe must say healthy (and must never hang:
    it is watchdog-bounded by construction)."""
    assert policy.health_probe(tp8_mesh, "tp") is True


def test_scheduler_describe_slot():
    from triton_dist_tpu.megakernel.scheduler import (
        describe_slot, schedule_mc)

    s = schedule_mc(5, [0, 0, 1, 2, 3], [1, 2, 3, 3, 4], num_cores=2)
    seen = set()
    for q in range(s["queue"].shape[0]):
        for c in range(2):
            d = describe_slot(s, q, c)
            assert d["merged_index"] == q * 2 + c
            if d["task"] >= 0:
                seen.add(d["task"])
                assert isinstance(d["waits_on_edges"], list)
                assert isinstance(d["signals_edges"], list)
    assert seen == {0, 1, 2, 3, 4}
    with pytest.raises(IndexError):
        describe_slot(s, 10 ** 6, 0)


def test_fault_plan_registry_complete():
    names = faults.battery()
    for required in ("delayed_dma", "dropped_signal", "dup_signal",
                     "skewed_barrier", "dropped_edge", "fail_kth_call"):
        assert required in names
    with pytest.raises(KeyError):
        faults.get_plan("no_such_plan")
