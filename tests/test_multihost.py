"""Multi-host bring-up integration test (VERDICT r3 missing #3).

Reference: the ``scripts/launch.sh`` + torchrun rendezvous path that
every reference test rides. Here ``scripts/launch.py`` spawns 2 real
processes x 4 virtual CPU devices with a live jax.distributed
coordination service and cross-process (Gloo) collectives — the
localhost stand-in for a 2-host pod slice.
"""

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


def test_two_process_launch():
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "launch.py"),
         "--nproc", "2", "--devices-per-proc", "4",
         os.path.join(HERE, "multihost_worker.py")],
        capture_output=True, text=True, timeout=900,
        env={k: v for k, v in os.environ.items()
             if k not in ("JAX_PLATFORMS", "XLA_FLAGS")})
    ok = [l for l in r.stdout.splitlines() if l.startswith("RESULT_OK")]
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert len(ok) == 2, (r.stdout[-2000:], r.stderr[-2000:])
