"""Chunked-prefill + disaggregated-serving battery.

Covers the serving split of ROADMAP Open item 1: fixed-shape bucketed
chunked prefill (jit cache bounded by the bucket count — never by the
distinct-prompt-length count), the prefill-worker/decode-worker role
split with whole-page KV migration over the one-sided p2p path, and
the containment story (a dropped or wedged migration fails one
request, never the server). Everything token-exact against the
sequential ``Engine.serve`` oracle; everything seeded.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu.models import Engine, ModelConfig, dense
from triton_dist_tpu.ops.chunked_prefill import plan_chunks
from triton_dist_tpu.resilience import faults
from triton_dist_tpu.resilience.watchdog import CommTimeoutError
from triton_dist_tpu.serving import (
    DisaggServingEngine, OutOfPagesError, PagedKVCache, ServingEngine,
)

TP = 4
CFG = ModelConfig.tiny()
MAX_LEN = 64
PAGE = 8
BUCKETS = (4, 16)
VOCAB = CFG.vocab_size


@pytest.fixture(scope="module")
def engine():
    mesh = Mesh(np.array(jax.devices()[:TP]), ("tp",))
    return Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=3)


@pytest.fixture(scope="module")
def role_engines():
    """Disjoint mesh slices sharing ONE weight pytree — the
    prefill-worker / decode-worker pair."""
    params = dense.init_params(jax.random.PRNGKey(3), CFG)
    devs = jax.devices()
    pf = Engine(CFG, Mesh(np.array(devs[:2]), ("tp",)), mode="xla",
                max_len=MAX_LEN, params=params)
    dec = Engine(CFG, Mesh(np.array(devs[2:4]), ("tp",)), mode="xla",
                 max_len=MAX_LEN, params=params)
    return pf, dec


def _baseline(engine, prompt, gen_len):
    n = engine.mesh.shape[engine.axis]
    ids = jnp.asarray(np.tile(np.asarray([prompt], np.int32), (n, 1)))
    return np.asarray(engine.serve(ids, gen_len=gen_len))[0].tolist()


# ---------------------------------------------------------------------------
# chunk planning (pure host logic)
# ---------------------------------------------------------------------------

def test_plan_chunks_deterministic_cover():
    for n in range(1, 40):
        plan = plan_chunks(n, BUCKETS)
        assert sum(v for _, v in plan) == n
        assert all(b in BUCKETS and 1 <= v <= b for b, v in plan)
        assert plan == plan_chunks(n, BUCKETS), "must be deterministic"
    # largest-fit greedy with a padded tail
    assert plan_chunks(21, BUCKETS) == [(16, 16), (4, 4), (4, 1)]
    assert plan_chunks(3, BUCKETS) == [(4, 3)]
    with pytest.raises(ValueError):
        plan_chunks(4, ())


# ---------------------------------------------------------------------------
# fixed-shape chunked prefill (in-place, single engine)
# ---------------------------------------------------------------------------

def test_chunked_token_exact_across_bucket_edges(engine):
    """Prompt lengths straddling every bucket edge (b-1 / b / b+1):
    greedy tokens equal the monolithic Engine.serve run — chunk
    boundaries are invisible."""
    lens = sorted({max(b + d, 1) for b in BUCKETS for d in (-1, 0, 1)})
    prompts = [[int(t) for t in
                np.random.RandomState(n).randint(0, VOCAB, n)]
               for n in lens]
    want = [_baseline(engine, p, 4) for p in prompts]
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        prefill_buckets=BUCKETS)
    assert srv.generate(prompts, max_new_tokens=4) == want


def test_chunked_jit_cache_bounded_by_buckets(engine):
    """The compile-count gate: after warmup over the buckets, UNSEEN
    prompt lengths cause zero new prefill or decode compilations (the
    prefill cache is bounded by the bucket count; monolithic prefill
    grows per distinct length)."""
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        prefill_buckets=BUCKETS)
    rng = np.random.RandomState(11)
    srv.generate([[1, 2, 3], list(range(20))], max_new_tokens=2)
    pre, dec = srv.prefill_cache_size(), srv.decode_cache_size()
    assert pre <= len(BUCKETS)
    for n in (2, 6, 9, 13, 19, 23):        # unseen lengths + a resume mix
        srv.submit([int(t) for t in rng.randint(0, VOCAB, n)],
                   max_new_tokens=2)
        srv.step()
    srv.run()
    assert srv.prefill_cache_size() == pre, "prefill re-specialized"
    assert srv.decode_cache_size() == dec, "decode re-specialized"
    st = srv.stats()
    assert st["prefill_cache_size"] == pre
    assert st["prefill_chunks"] > 0 and st["prefill_buckets"] == list(
        BUCKETS)


def test_chunked_interleaves_with_decode(engine):
    """A long prompt no longer monopolizes the dispatch: while it
    chunk-streams, an already-running request keeps decoding (decode
    dispatches happen between its chunks)."""
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        prefill_buckets=(4,))
    short = srv.submit([1, 2], max_new_tokens=8)
    srv.step()                       # short admitted + decoding
    long = srv.submit(list(range(17)), max_new_tokens=2)  # 5 chunks
    progress = []
    while long.status in ("queued", "prefill"):
        srv.step()
        progress.append(len(short.tokens))
    assert progress[-1] > progress[0], (
        "short request made no decode progress during the long "
        "prompt's chunk stream")
    srv.run()
    assert short.tokens == _baseline(engine, [1, 2], 8)
    assert long.tokens == _baseline(engine, list(range(17)), 2)


def test_chunked_prefix_reuse_skips_resident_pages(engine):
    """Chunked × prefix-reuse: the second sharer's chunk stream starts
    at the first non-shared page (fewer chunks), shared pages are
    never re-blitted while a live reader holds them, and tokens stay
    exact. The BlockManager.prefix_hits assertion of satellite 2."""
    shared = list(range(1, 17))              # two full pages
    p1, p2 = shared + [30, 31], shared + [40]
    want = [_baseline(engine, p1, 3), _baseline(engine, p2, 3)]
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        prefill_buckets=BUCKETS, prefix_reuse=True)
    h1 = srv.submit(p1, max_new_tokens=3)
    srv.step()
    srv.step()                               # p1 fully prefilled (16+4)
    h2 = srv.submit(p2, max_new_tokens=3)    # while h1 still decodes
    srv.step()
    assert srv.manager.prefix_hits(h2.slot) == 2, (
        "second sharer must hit both full prefix pages")
    srv.run()
    assert [h1.tokens, h2.tokens] == want
    assert srv.manager.stats["prefix_hits"] >= 2
    # h2 computed only its non-shared tail: ONE bucket-4 chunk starting
    # at the first non-shared page, vs h1's full 16+4 stream.
    assert h1.chunks == [(0, 16, 16), (16, 4, 2)], h1.chunks
    assert h2.chunks == [(16, 4, 1)], h2.chunks


def test_chunked_prefix_concurrent_admission_no_unwritten_share(engine):
    """Two same-prefix requests admitted in ONE tick: the second must
    not attend the first's still-unwritten prefix pages (prefix
    entries publish only at content-resident commit). Both stay
    token-exact; the second computes its own copy (no hits) because it
    admitted inside the first's chunk-stream window."""
    shared = list(range(1, 17))
    p1, p2 = shared + [30, 31], shared + [40]
    want = [_baseline(engine, p1, 3), _baseline(engine, p2, 3)]
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        prefill_buckets=BUCKETS, prefix_reuse=True)
    h1 = srv.submit(p1, max_new_tokens=3)
    h2 = srv.submit(p2, max_new_tokens=3)   # same tick — mid-stream
    srv.run()
    assert [h1.tokens, h2.tokens] == want
    # Both full streams ran (no premature sharing): 16+4 chunks each.
    assert h1.chunks[0] == (0, 16, 16) and h2.chunks[0] == (0, 16, 16)
    # A THIRD same-prefix request after commit does share.
    h3 = srv.submit(shared + [50], max_new_tokens=3)
    srv.run()
    assert h3.tokens == _baseline(engine, shared + [50], 3)
    assert h3.chunks[0][0] == 16, "post-commit sharer should skip"


def test_chunked_preempt_resume_deterministic(engine):
    """A preempted request re-prefills prompt + generated-so-far
    through the SAME deterministic bucket plan and ends token-exact."""
    prompts = [[1, 2, 3, 4, 5, 6], [7, 8, 9, 10, 11, 12]]
    want = [_baseline(engine, p, 4) for p in prompts]
    srv = ServingEngine(engine, num_slots=2, page=PAGE, num_pages=3,
                        prefill_buckets=BUCKETS)
    hs = [srv.submit(p, max_new_tokens=4) for p in prompts]
    srv.run()
    assert [h.tokens for h in hs] == want
    assert srv.stats()["preemptions"] >= 1
    # The last chunk stream (the resume) followed the deterministic
    # plan of its lane (prompt + generated-so-far at preemption time).
    resumed = max(hs, key=lambda h: len(h.lane))
    assert len(resumed.lane) > len(resumed.request.prompt), (
        "expected a resumed lane carrying generated tokens")
    start = resumed.chunks[0][0]
    assert [(b, v) for _, b, v in resumed.chunks] == plan_chunks(
        len(resumed.lane) - start, BUCKETS), (
        "resume deviated from the plan")


def test_chunked_wedged_chunk_fails_one_request(engine):
    """A dropped chunk dispatch (fault plan) fails the admitting
    request only; the running survivor stays token-exact."""
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        prefill_buckets=BUCKETS)
    ok = srv.submit([1, 2, 3], max_new_tokens=5)
    srv.step()
    doomed = srv.submit([4, 5], max_new_tokens=3)
    with faults.inject(faults.get_plan("fail_kth_call",
                                       op="chunked_prefill", k=0)):
        srv.run()
    assert doomed.status == "failed"
    assert isinstance(doomed.error, faults.InjectedFault)
    assert ok.status == "done"
    assert ok.tokens == _baseline(engine, [1, 2, 3], 5)
    assert srv.stats()["pool"]["used_pages"] == 0, "pages leaked"


# ---------------------------------------------------------------------------
# page-migration building blocks
# ---------------------------------------------------------------------------

def test_page_gather_scatter_bit_exact():
    """PagedKVCache.gather_pages → scatter_pages round-trips page
    bytes exactly under a REWRITTEN block table (different dst ids),
    with padding rows dumped into scratch."""
    rng = np.random.RandomState(0)
    src = PagedKVCache.empty(2, 6, 4, 2, 3, num_slots=1, p_max=3)
    src = dataclasses.replace(
        src,
        k_pages=jnp.asarray(rng.randn(2, 6, 2, 4, 3), jnp.float32),
        v_pages=jnp.asarray(rng.randn(2, 6, 2, 4, 3), jnp.float32))
    dst = PagedKVCache.empty(2, 6, 4, 2, 3, num_slots=1, p_max=3)
    src_ids = jnp.asarray([1, 3, 0], jnp.int32)       # pad -> scratch
    dst_ids = jnp.asarray([4, 2, 0], jnp.int32)       # rewritten table
    k_pay, v_pay = src.gather_pages(src_ids)
    dst = dst.scatter_pages(k_pay, v_pay, dst_ids)
    np.testing.assert_array_equal(np.asarray(dst.k_pages)[:, 4],
                                  np.asarray(src.k_pages)[:, 1])
    np.testing.assert_array_equal(np.asarray(dst.v_pages)[:, 2],
                                  np.asarray(src.v_pages)[:, 3])
    # untouched pages stay zero
    np.testing.assert_array_equal(np.asarray(dst.k_pages)[:, 5], 0.0)


def test_migrate_pages_host_bridge_put(role_engines):
    """ops/p2p.migrate_pages_host carries a page payload bit-exactly
    from the prefill role's rank to the decode role's over the bridge
    mesh."""
    pf, dec = role_engines
    bridge = Mesh(np.array([pf.mesh.devices.flat[0],
                            dec.mesh.devices.flat[0]]), ("role",))
    from triton_dist_tpu.ops.p2p import migrate_pages_host

    rng = np.random.RandomState(1)
    k = rng.randn(2, 3, 2, 4, 5).astype(np.float32)
    v = rng.randn(2, 3, 2, 4, 5).astype(np.float32)
    k2, v2 = migrate_pages_host(jnp.asarray(k), jnp.asarray(v), bridge)
    np.testing.assert_array_equal(k2, k)
    np.testing.assert_array_equal(v2, v)


# ---------------------------------------------------------------------------
# disaggregated serving (prefill worker | decode worker)
# ---------------------------------------------------------------------------

def _disagg(pf, dec, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page", PAGE)
    kw.setdefault("prefill_buckets", BUCKETS)
    return DisaggServingEngine(dec, prefill_engine=pf, **kw)


def test_disagg_token_exact_vs_solo(role_engines):
    """Disjoint-role serving with p2p page migration: every request's
    greedy tokens equal its solo Engine.serve run (bucket edges
    included)."""
    pf, dec = role_engines
    lens = sorted({max(b + d, 1) for b in BUCKETS for d in (-1, 0, 1)})
    prompts = [[int(t) for t in
                np.random.RandomState(100 + n).randint(0, VOCAB, n)]
               for n in lens]
    want = [_baseline(dec, p, 4) for p in prompts]
    srv = _disagg(pf, dec)
    assert srv.migration == "p2p"
    assert srv.generate(prompts, max_new_tokens=4) == want
    st = srv.stats()
    assert st["roles"] == "prefill|decode/disjoint"
    assert st["migrated_pages"] == sum(
        -(-len(p) // PAGE) for p in prompts)
    assert st["pool"]["used_pages"] == 0
    assert st["prefill_pool"]["used_pages"] == 0, "staging leaked"


def test_disagg_migration_bit_exact_rewritten_tables(role_engines):
    """The decode pool's migrated pages hold byte-identical KV to the
    prefill worker's staging pages, under a REWRITTEN (receiver-side)
    block table."""
    pf, dec = role_engines
    srv = _disagg(pf, dec)
    record = {}
    orig = srv._scatter

    def spy(cache, k_pay, v_pay, ids):
        record["k"], record["ids"] = np.asarray(k_pay), np.asarray(ids)
        return orig(cache, k_pay, v_pay, ids)

    srv._scatter = spy
    # Shift the decode allocator (a parked reservation outside the
    # scheduler's slot range) so src and dst page ids must differ.
    srv.manager.alloc_prefill(99, list(range(PAGE)))
    prompt = list(range(1, 14))                       # 2 pages
    h = srv.submit(prompt, max_new_tokens=2)
    while h.status in ("queued", "prefill"):
        srv.step()
    assert h.status == "migrating"
    src_ids = np.asarray(
        srv.prefill_worker.manager.table_row(h.slot), np.int32)
    k_src, _ = srv.prefill_worker.extract(src_ids)
    record["src"], record["src_ids"] = np.asarray(k_src), src_ids
    # Complete the handoff WITHOUT a decode tick, so the pool still
    # holds exactly the migrated bytes when inspected.
    srv._complete_migrations()
    assert h.status == "running"
    n_pages = -(-len(prompt) // PAGE)
    dst_ids = record["ids"][:n_pages]
    assert not np.array_equal(dst_ids, record["src_ids"][:n_pages]), (
        "block table was not rewritten on the receiver")
    np.testing.assert_array_equal(record["k"], record["src"],
                                  err_msg="migrated payload drifted")
    dec_pool = np.asarray(srv.cache.k_pages)
    for i in range(n_pages):
        np.testing.assert_array_equal(
            dec_pool[:, dst_ids[i]], record["src"][:, i],
            err_msg=f"page {i} bytes differ after scatter")
    srv.manager.free_slot(99)
    srv.run()
    assert h.tokens == _baseline(dec, prompt, 2)


def test_disagg_prefix_migrates_once(role_engines):
    """Refcounted prefix pages migrate ONCE: the second sharer's
    handoff skips decode-side-resident pages (and its chunk stream
    skips computing them)."""
    pf, dec = role_engines
    srv = _disagg(pf, dec, prefix_reuse=True)
    shared = list(range(1, 17))                       # two full pages
    p1, p2 = shared + [30, 31], shared + [40]
    want = [_baseline(dec, p1, 3), _baseline(dec, p2, 3)]
    h1 = srv.submit(p1, max_new_tokens=3)
    srv.run()
    first = srv.stats()["migrated_pages"]
    assert first == 3
    h2 = srv.submit(p2, max_new_tokens=3)
    srv.run()
    assert [h1.tokens, h2.tokens] == want
    assert srv.stats()["migrated_pages"] == first + 1, (
        "shared prefix pages re-migrated")


def test_disagg_preempt_resume(role_engines):
    """Mid-decode preemption on the decode worker resumes through the
    prefill worker deterministically — and re-migrates."""
    pf, dec = role_engines
    prompts = [[1, 2, 3, 4, 5, 6], [7, 8, 9, 10, 11, 12]]
    want = [_baseline(dec, p, 4) for p in prompts]
    srv = _disagg(pf, dec, num_pages=3)
    hs = [srv.submit(p, max_new_tokens=4) for p in prompts]
    srv.run()
    assert [h.tokens for h in hs] == want
    assert srv.stats()["preemptions"] >= 1
    resumed = max(hs, key=lambda h: len(h.lane))
    start = resumed.chunks[0][0]
    assert [(b, v) for _, b, v in resumed.chunks] == plan_chunks(
        len(resumed.lane) - start, BUCKETS)


def test_disagg_dropped_migration_fails_one_request(role_engines):
    """Fault-plan dropped migration: one request fails, survivors stay
    token-exact, no page leaks on either pool — the server outlives
    its transport."""
    pf, dec = role_engines
    srv = _disagg(pf, dec)
    prompts = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 10]]
    want = [_baseline(dec, p, 3) for p in prompts]
    hs = [srv.submit(p, max_new_tokens=3) for p in prompts]
    with faults.inject(faults.get_plan("fail_kth_call",
                                       op="page_migration", k=0)):
        srv.run()
    statuses = [h.status for h in hs]
    assert statuses.count("failed") == 1, statuses
    for h, w in zip(hs, want):
        if h.status == "failed":
            assert isinstance(h.error, faults.InjectedFault)
        else:
            assert h.status == "done" and h.tokens == w
    st = srv.stats()
    assert st["pool"]["used_pages"] == 0
    assert st["prefill_pool"]["used_pages"] == 0


def test_disagg_dropped_migration_no_prefix_poison(role_engines):
    """A dropped migration must NOT leave decode-side prefix entries
    for pages whose payload never arrived: a later same-prefix request
    migrates its own copy and stays token-exact."""
    pf, dec = role_engines
    srv = _disagg(pf, dec, prefix_reuse=True)
    shared = list(range(1, 17))                       # two full pages
    doomed = srv.submit(shared + [30], max_new_tokens=3)
    with faults.inject(faults.get_plan("fail_kth_call",
                                       op="page_migration", k=0)):
        srv.run()
    assert doomed.status == "failed"
    later = srv.submit(shared + [40], max_new_tokens=3)
    srv.run()
    assert later.status == "done"
    assert later.tokens == _baseline(dec, shared + [40], 3)
    # All 3 of later's pages migrated: nothing stale to hit.
    assert srv.stats()["migrated_pages"] == 3


def test_disagg_wedged_migration_times_out_one_request(role_engines):
    """A migration that never completes (watchdog timeout) fails its
    request with CommTimeoutError; the server keeps serving."""
    pf, dec = role_engines
    srv = _disagg(pf, dec, timeout_s=60.0)
    real = srv._scatter

    def wedged(cache, k, v, ids):
        raise CommTimeoutError(op="serving.page_migration", rank=0,
                               timeout_s=0.1, progress=None)

    doomed = srv.submit([1, 2, 3], max_new_tokens=3)
    srv._scatter = wedged
    while doomed.status in ("queued", "prefill"):
        srv.step()
    srv.step()                     # the migration tick — wedged
    srv._scatter = real
    fresh = srv.submit([4, 5], max_new_tokens=2)
    srv.run()
    assert doomed.status == "timeout"
    assert isinstance(doomed.error, CommTimeoutError)
    assert fresh.status == "done"
    assert fresh.tokens == _baseline(dec, [4, 5], 2)
    assert srv.stats()["comm_timeouts"] == 1


def test_disagg_degenerate_single_mesh(engine):
    """Single-role degenerate mode: one engine plays both roles on one
    mesh — chunked prefill + local page migration, same exactness and
    cache bounds."""
    srv = DisaggServingEngine(engine, num_slots=2, page=PAGE,
                              prefill_buckets=BUCKETS)
    assert srv.migration == "local"
    prompts = [[1, 2, 3], list(range(1, 19))]
    want = [_baseline(engine, p, 3) for p in prompts]
    assert srv.generate(prompts, max_new_tokens=3) == want
    st = srv.stats()
    assert st["roles"] == "prefill+decode/colocated"
    assert st["migrated_pages"] == 4
    assert srv.prefill_cache_size() <= len(BUCKETS)
    assert srv.decode_cache_size() == 1


def test_disagg_decode_pool_backpressure(role_engines):
    """A dry DECODE pool at handoff requeues (staging released), and
    the request completes once pages free — no deadlock, no leak."""
    pf, dec = role_engines
    srv = _disagg(pf, dec, num_pages=2)       # one usable decode page
    h1 = srv.submit([1, 2, 3], max_new_tokens=3)
    h2 = srv.submit([4, 5, 6], max_new_tokens=3)
    srv.run()
    assert h1.status == "done" and h2.status == "done"
    assert srv.stats()["admit_stalls"] >= 1
    want = [_baseline(dec, [1, 2, 3], 3), _baseline(dec, [4, 5, 6], 3)]
    assert [h1.tokens, h2.tokens] == want


def test_disagg_rejects_megakernel():
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    cfg = ModelConfig.tiny(vocab_size=128)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    mk = MegaKernelEngine(cfg, mesh, batch=2, max_len=16, tile_w=16,
                          t_tile=16)
    with pytest.raises(ValueError, match="megakernel"):
        DisaggServingEngine(mk)
    with pytest.raises(ValueError, match="prefill_buckets mismatch"):
        ServingEngine(mk, prefill_buckets=(4,))
