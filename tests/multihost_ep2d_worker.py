"""Inner SPMD worker for the multi-host EP-2D dispatch entry
(dryrun_multichip; launched by ``scripts/launch.py`` as 2 processes x
4 virtual CPU devices — the localhost analogue of a 2-node pod slice
where DCN crosses processes and ICI stays inside one).

Runs the hierarchical ``ll2d`` MoE decode dispatch over the GLOBAL
(dp=2, tp=4) mesh — the DCN hop is a genuine cross-process exchange —
and token-checks it against the zero-communication ``"ar"`` oracle on
the same replicated batch. Hop impl is ``"xla"``: interpret-mode
Pallas inside a global-mesh shard_map deadlocks by construction in a
multi-process run (the kernel gate is a ``threading.Barrier`` sized to
the full axis env while each process hosts only half the callback
threads — see tests/multihost_worker.py), and the xla hop carries the
identical wire payload.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from triton_dist_tpu.utils.distributed import (  # noqa: E402
    initialize_distributed, dist_print,
)

initialize_distributed()   # reads COORDINATOR_ADDRESS/NUM_PROCESSES/...

import jax                                       # noqa: E402
import jax.numpy as jnp                          # noqa: E402
import numpy as np                               # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

import triton_dist_tpu as tdt                    # noqa: E402
from triton_dist_tpu.layers import ep_moe        # noqa: E402
from triton_dist_tpu.models.config import ModelConfig  # noqa: E402
from triton_dist_tpu.ops.ep_a2a import create_ep2d_context  # noqa: E402

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()

# dp is the outer (DCN) axis: each process' 4 local devices form its
# tp (ICI) group — expert ownership is outer-major over the 8 ranks.
mesh = tdt.make_mesh(dp=2, tp=4, devices=jax.devices())
mctx = tdt.MeshContext.from_mesh(mesh)
cfg = ModelConfig.tiny_moe(hidden_size=32, moe_intermediate_size=16,
                           num_experts=8, num_experts_per_tok=2)
ctx2d = create_ep2d_context(mctx, num_experts=cfg.num_experts,
                            topk=cfg.num_experts_per_tok,
                            outer_axis="dp", inner_axis="tp",
                            impl="xla")
axis = ("dp", "tp")
params = ep_moe.init(jax.random.PRNGKey(3), cfg)
specs = {name: ep_moe.param_specs(axis)[name] for name in params}
# Explicit global placement (the multihost contract: host arrays are
# identical on every process, so device_put to a cross-process
# NamedSharding is well defined on each).
params = {name: jax.device_put(v, NamedSharding(mesh, specs[name]))
          for name, v in params.items()}
x = jax.device_put(
    jax.random.normal(jax.random.PRNGKey(5), (4, cfg.hidden_size),
                      jnp.float32),
    NamedSharding(mesh, P(None, None)))


def run(transport):
    f = jax.jit(jax.shard_map(
        lambda p, v: ep_moe.fwd_decode(
            p, v, topk=cfg.num_experts_per_tok, axis=axis,
            transport=transport, ep_ctx=ctx2d),
        mesh=mesh, in_specs=(specs, P(None, None)),
        out_specs=P(None, None), check_vma=False))
    return np.asarray(jax.device_get(f(params, x)))


ar = run("ar")            # zero-dispatch oracle (the old fallback)
ll2d = run("ll2d")        # 2-hop: ICI intra-process, DCN across
np.testing.assert_allclose(ll2d, ar, rtol=2e-2, atol=2e-2)
# Decode-level acceptance: the wire quantization must not perturb the
# greedy "token" (argmax over the hidden readout) on any row.
assert np.array_equal(ll2d.argmax(-1), ar.argmax(-1)), (
    ll2d.argmax(-1), ar.argmax(-1))
dist_print("EP-2D multihost dispatch OK (ll2d == ar across DCN)",
           allowed_ranks="all")

print(f"RESULT_OK rank={jax.process_index()}", flush=True)
