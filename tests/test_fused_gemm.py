"""Fused overlapped GEMM ops vs XLA-collective oracles.

Reference test pattern: ``test/nvidia/test_ag_gemm.py`` /
``test_gemm_rs.py`` / ``test_gemm_ar.py`` — fused kernel vs torch
collective + matmul with allclose.

NOTE on shapes: TPU interpret mode on the CPU test mesh deadlocks when a
single pallas buffer exceeds ~64 KB/device (XLA:CPU host-callback operand
materialization starves on a 1-core box). Kernel logic is shape-agnostic;
these tests pick shapes that keep every buffer (incl. HBM workspaces)
under that limit. Full-size validation happens on real TPU via bench.py.
"""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops import (
    ag_gemm, ag_gemm_ref, create_ag_gemm_context,
    gemm_rs, gemm_rs_ref, create_gemm_rs_context,
    gemm_ar, gemm_ar_ref, create_gemm_ar_context,
)
from triton_dist_tpu.utils.testing import spmd, assert_allclose


def _rand(shape, seed, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype)


@pytest.mark.parametrize("m,k,n_dim", [(256, 32, 128), (256, 64, 64)])
def test_ag_gemm(tp8_mesh, tp8_ctx, m, k, n_dim):
    a = _rand((m, k), 0)          # sharded on dim0 (rows)
    b = _rand((k, n_dim), 1)      # sharded on dim1 (column-parallel)
    ctx = create_ag_gemm_context(tp8_ctx, block_m=16, block_n=8)

    f = spmd(tp8_mesh, lambda x, w: ag_gemm(x, w, ctx),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    g = spmd(tp8_mesh, lambda x, w: ag_gemm_ref(x, w),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4)


def test_ag_gemm_return_ag(tp8_mesh, tp8_ctx):
    a = _rand((256, 32), 0)
    b = _rand((32, 64), 1)
    ctx = create_ag_gemm_context(tp8_ctx, block_m=32, block_n=8)
    f = spmd(tp8_mesh, lambda x, w: ag_gemm(x, w, ctx, return_ag=True),
             (P("tp", None), P(None, "tp")), (P(None, "tp"), P(None, None)))
    c, a_full = f(a, b)
    assert_allclose(a_full, a)
    g = spmd(tp8_mesh, lambda x, w: ag_gemm_ref(x, w),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    assert_allclose(c, g(a, b), rtol=1e-4, atol=1e-4)


def test_gemm_rs(tp8_mesh, tp8_ctx):
    m, k, n_dim = 256, 256, 64
    a = _rand((m, k), 2)          # K sharded on dim1
    b = _rand((k, n_dim), 3)      # K sharded on dim0 (row-parallel)
    ctx = create_gemm_rs_context(tp8_ctx, block_m=32, block_n=32)

    f = spmd(tp8_mesh, lambda x, w: gemm_rs(x, w, ctx),
             (P(None, "tp"), P("tp", None)), P("tp", None))
    g = spmd(tp8_mesh, lambda x, w: gemm_rs_ref(x, w),
             (P(None, "tp"), P("tp", None)), P("tp", None))
    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4)


def test_gemm_ar(tp8_mesh, tp8_ctx):
    m, k, n_dim = 16, 256, 64
    a = _rand((m, k), 4)
    b = _rand((k, n_dim), 5)
    ctx = create_gemm_ar_context(tp8_ctx, block_n=32)

    f = spmd(tp8_mesh, lambda x, w: gemm_ar(x, w, ctx),
             (P(None, "tp"), P("tp", None)), P(None, None))
    g = spmd(tp8_mesh, lambda x, w: gemm_ar_ref(x, w),
             (P(None, "tp"), P("tp", None)), P(None, None))
    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4)


def test_gemm_rs_bf16(tp8_mesh, tp8_ctx):
    m, k, n_dim = 256, 256, 64
    a = _rand((m, k), 8, jnp.bfloat16)
    b = _rand((k, n_dim), 9, jnp.bfloat16)
    ctx = create_gemm_rs_context(tp8_ctx, block_m=32, block_n=32)
    f = spmd(tp8_mesh, lambda x, w: gemm_rs(x, w, ctx),
             (P(None, "tp"), P("tp", None)), P("tp", None))
    g = spmd(tp8_mesh, lambda x, w: gemm_rs_ref(x, w),
             (P(None, "tp"), P("tp", None)), P("tp", None))
    assert_allclose(jnp.asarray(f(a, b), jnp.float32),
                    jnp.asarray(g(a, b), jnp.float32), rtol=5e-2, atol=5e-1)


@pytest.mark.parametrize("variant", ["ll", "one_shot"])
def test_gemm_ar_variants(tp8_mesh, tp8_ctx, variant):
    """Both exchange schemes vs the psum oracle, with n_j > 1 so the ll
    variant's lagged per-tile reduce pipeline is actually exercised
    (reference: low_latency_gemm_allreduce_op, gemm_allreduce.py:669)."""
    m, k, n_dim = 16, 128, 128
    a = _rand((m, k), 40)
    b = _rand((k, n_dim), 41)
    ctx = create_gemm_ar_context(tp8_ctx, block_n=16, block_k=8,
                                 variant=variant)
    f = spmd(tp8_mesh, lambda x, w: gemm_ar(x, w, ctx),
             (P(None, "tp"), P("tp", None)), P(None, None))
    g = spmd(tp8_mesh, lambda x, w: gemm_ar_ref(x, w),
             (P(None, "tp"), P("tp", None)), P(None, None))
    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4)


def test_gemm_ar_bf16(tp8_mesh, tp8_ctx):
    m, k, n_dim = 16, 256, 64
    a = _rand((m, k), 10, jnp.bfloat16)
    b = _rand((k, n_dim), 11, jnp.bfloat16)
    ctx = create_gemm_ar_context(tp8_ctx, block_n=32)
    f = spmd(tp8_mesh, lambda x, w: gemm_ar(x, w, ctx),
             (P(None, "tp"), P("tp", None)), P(None, None))
    g = spmd(tp8_mesh, lambda x, w: gemm_ar_ref(x, w),
             (P(None, "tp"), P("tp", None)), P(None, None))
    assert_allclose(jnp.asarray(f(a, b), jnp.float32),
                    jnp.asarray(g(a, b), jnp.float32), rtol=5e-2, atol=5e-1)


def test_ag_gemm_ktiled(tp8_mesh, tp8_ctx):
    """Exercise n_k > 1 together with n_j > 1 (regression: the A panel
    must stay valid across the whole j sweep, not just j == 0)."""
    a = _rand((256, 64), 12)
    b = _rand((64, 64), 13)
    ctx = create_ag_gemm_context(tp8_ctx, block_m=16, block_n=4, block_k=16)
    f = spmd(tp8_mesh, lambda x, w: ag_gemm(x, w, ctx),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    g = spmd(tp8_mesh, lambda x, w: ag_gemm_ref(x, w),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4)


def test_ag_gemm_bf16(tp8_mesh, tp8_ctx):
    a = _rand((256, 32), 6, jnp.bfloat16)
    b = _rand((32, 64), 7, jnp.bfloat16)
    ctx = create_ag_gemm_context(tp8_ctx, block_m=32, block_n=8)
    f = spmd(tp8_mesh, lambda x, w: ag_gemm(x, w, ctx),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    g = spmd(tp8_mesh, lambda x, w: ag_gemm_ref(x, w),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    assert_allclose(jnp.asarray(f(a, b), jnp.float32),
                    jnp.asarray(g(a, b), jnp.float32), rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("variant", ["panel", "pipelined"])
def test_ag_gemm_sim_ranks(variant):
    """Self-simulated ring on a 1-device mesh (the bench.py single-chip
    overlap proxy): the full ring schedule runs with self-targeted puts
    and must reproduce the plain matmul."""
    import numpy as np
    from jax.sharding import Mesh
    from triton_dist_tpu.parallel.mesh import MeshContext

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("tp",))
    ctx1 = MeshContext.from_mesh(mesh1)
    a = _rand((256, 32), 50)
    b = _rand((32, 64), 51)
    ctx = create_ag_gemm_context(ctx1, block_m=16, block_n=8,
                                 variant=variant)
    f = spmd(mesh1, lambda x, w: ag_gemm(x, w, ctx, sim_ranks=4),
             (P(None, None), P(None, None)), P(None, None))
    want = jnp.dot(a, b)
    assert_allclose(f(a, b), want, rtol=1e-4, atol=1e-4)


def test_gemm_rs_sim_ranks():
    """Self-simulated ring for gemm_rs: full schedule and traffic with
    received partials runtime-weighted to zero — the output must be the
    plain local GEMM (the n=1 reduce)."""
    import numpy as np
    from jax.sharding import Mesh
    from triton_dist_tpu.parallel.mesh import MeshContext

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("tp",))
    ctx1 = MeshContext.from_mesh(mesh1)
    a = _rand((256, 32), 54)
    b = _rand((32, 64), 55)
    ctx = create_gemm_rs_context(ctx1, block_m=16, block_n=16)
    f = spmd(mesh1, lambda x, w: gemm_rs(x, w, ctx, sim_ranks=4),
             (P(None, None), P(None, None)), P(None, None))
    assert_allclose(f(a, b), jnp.dot(a, b), rtol=1e-4, atol=1e-4)


def test_ag_gemm_sim_ranks_return_ag():
    """Sim mode must also fill the gather workspace correctly."""
    import numpy as np
    from jax.sharding import Mesh
    from triton_dist_tpu.parallel.mesh import MeshContext

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("tp",))
    ctx1 = MeshContext.from_mesh(mesh1)
    a = _rand((128, 32), 52)
    b = _rand((32, 64), 53)
    ctx = create_ag_gemm_context(ctx1, block_m=16, block_n=8)
    f = spmd(mesh1,
             lambda x, w: ag_gemm(x, w, ctx, sim_ranks=4, return_ag=True),
             (P(None, None), P(None, None)), (P(None, None), P(None, None)))
    c, a_full = f(a, b)
    assert_allclose(a_full, a)
    assert_allclose(c, jnp.dot(a, b), rtol=1e-4, atol=1e-4)


def test_ag_gemm_pipelined_variant(tp8_mesh, tp8_ctx):
    """The opt-in pipelined variant must agree with the oracle."""
    a = _rand((256, 64), 30)
    b = _rand((64, 64), 31)
    ctx = create_ag_gemm_context(tp8_ctx, block_m=16, block_n=4,
                                 block_k=16, variant="pipelined")
    f = spmd(tp8_mesh, lambda x, w: ag_gemm(x, w, ctx),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    g = spmd(tp8_mesh, lambda x, w: ag_gemm_ref(x, w),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("variant", ["ll", "one_shot"])
def test_gemm_ar_sim_ranks(variant):
    """Self-simulated exchange for gemm_ar (both schemes): full push +
    per-slot reduce schedule with peer slots runtime-weighted to zero —
    the output must be the plain local GEMM."""
    import numpy as np
    from jax.sharding import Mesh
    from triton_dist_tpu.parallel.mesh import MeshContext

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("tp",))
    ctx1 = MeshContext.from_mesh(mesh1)
    a = _rand((16, 64), 56)
    b = _rand((64, 64), 57)
    ctx = create_gemm_ar_context(ctx1, block_n=16, block_k=16,
                                 variant=variant)
    f = spmd(mesh1, lambda x, w: gemm_ar(x, w, ctx, sim_ranks=4),
             (P(None, None), P(None, None)), P(None, None))
    assert_allclose(f(a, b), jnp.dot(a, b), rtol=1e-4, atol=1e-4)


def test_ag_gemm_2d(dp2tp4_mesh, dp2tp4_ctx):
    """Hierarchical dcn x ici AG+GEMM on the 2 x 4 mesh vs the flat
    two-axis gather oracle."""
    m, k, n_dim = 256, 32, 64
    a = _rand((m, k), 7)
    b = _rand((k, n_dim), 8)
    ctx = create_ag_gemm_context(dp2tp4_ctx, axis=("dp", "tp"),
                                 block_m=16, block_n=8)

    def oracle(x, w):
        x_full = jax.lax.all_gather(x, ("dp", "tp"), axis=0, tiled=True)
        return jnp.dot(x_full, w,
                       preferred_element_type=jnp.float32).astype(x.dtype)

    f = spmd(dp2tp4_mesh, lambda x, w: ag_gemm(x, w, ctx),
             (P(("dp", "tp"), None), P(None, ("dp", "tp"))),
             P(None, ("dp", "tp")))
    g = spmd(dp2tp4_mesh, oracle,
             (P(("dp", "tp"), None), P(None, ("dp", "tp"))),
             P(None, ("dp", "tp")))
    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4)


def test_ag_gemm_2d_return_ag(dp2tp4_mesh, dp2tp4_ctx):
    a = _rand((256, 32), 9)
    b = _rand((32, 32), 10)
    ctx = create_ag_gemm_context(dp2tp4_ctx, axis=("dp", "tp"),
                                 block_m=32, block_n=8)
    f = spmd(dp2tp4_mesh, lambda x, w: ag_gemm(x, w, ctx, return_ag=True),
             (P(("dp", "tp"), None), P(None, ("dp", "tp"))),
             (P(None, ("dp", "tp")), P(None, None)))
    c, a_full = f(a, b)
    assert_allclose(a_full, a)

    def oracle(x, w):
        x_full = jax.lax.all_gather(x, ("dp", "tp"), axis=0, tiled=True)
        return jnp.dot(x_full, w,
                       preferred_element_type=jnp.float32).astype(x.dtype)

    g = spmd(dp2tp4_mesh, oracle,
             (P(("dp", "tp"), None), P(None, ("dp", "tp"))),
             P(None, ("dp", "tp")))
    assert_allclose(c, g(a, b), rtol=1e-4, atol=1e-4)


def test_ag_gemm_2d_single_panel_buffer(dp2tp4_mesh, dp2tp4_ctx):
    """n_buf == 1 path (chunk_len == 1): arrival waits at chunk start."""
    m, k, n_dim = 128, 32, 32
    a = _rand((m, k), 11)
    b = _rand((k, n_dim), 12)
    # m_loc = 16 -> block_m 16 = one row tile; block_n/block_k cover
    # whole dims -> n_i = n_j = n_k = 1.
    ctx = create_ag_gemm_context(dp2tp4_ctx, axis=("dp", "tp"),
                                 block_m=16, block_n=32, block_k=32)

    def oracle(x, w):
        x_full = jax.lax.all_gather(x, ("dp", "tp"), axis=0, tiled=True)
        return jnp.dot(x_full, w,
                       preferred_element_type=jnp.float32).astype(x.dtype)

    f = spmd(dp2tp4_mesh, lambda x, w: ag_gemm(x, w, ctx),
             (P(("dp", "tp"), None), P(None, ("dp", "tp"))),
             P(None, ("dp", "tp")))
    g = spmd(dp2tp4_mesh, oracle,
             (P(("dp", "tp"), None), P(None, ("dp", "tp"))),
             P(None, ("dp", "tp")))
    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4)


def test_gemm_rs_2d(dp2tp4_mesh, dp2tp4_ctx):
    """Hierarchical dcn x ici GEMM+RS on the 2 x 4 mesh vs the flat
    two-axis psum_scatter oracle."""
    m, k, n_dim = 128, 64, 32
    a = _rand((m, k), 13)
    b = _rand((k, n_dim), 14)
    ctx = create_gemm_rs_context(dp2tp4_ctx, axis=("dp", "tp"),
                                 block_m=16, block_n=16, block_k=8)

    def oracle(x, w):
        partial = jnp.dot(x, w, preferred_element_type=jnp.float32)
        return jax.lax.psum_scatter(
            partial, ("dp", "tp"), scatter_dimension=0,
            tiled=True).astype(x.dtype)

    f = spmd(dp2tp4_mesh, lambda x, w: gemm_rs(x, w, ctx),
             (P(None, ("dp", "tp")), P(("dp", "tp"), None)),
             P(("dp", "tp"), None))
    g = spmd(dp2tp4_mesh, oracle,
             (P(None, ("dp", "tp")), P(("dp", "tp"), None)),
             P(("dp", "tp"), None))
    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4)


def test_gemm_rs_2d_single_tile(dp2tp4_mesh, dp2tp4_ctx):
    """One tile per chunk (n_i = n_j = 1) — put/fold at the same body."""
    m, k, n_dim = 128, 32, 16
    a = _rand((m, k), 15)
    b = _rand((k, n_dim), 16)
    ctx = create_gemm_rs_context(dp2tp4_ctx, axis=("dp", "tp"),
                                 block_m=16, block_n=16, block_k=32)

    def oracle(x, w):
        partial = jnp.dot(x, w, preferred_element_type=jnp.float32)
        return jax.lax.psum_scatter(
            partial, ("dp", "tp"), scatter_dimension=0,
            tiled=True).astype(x.dtype)

    f = spmd(dp2tp4_mesh, lambda x, w: gemm_rs(x, w, ctx),
             (P(None, ("dp", "tp")), P(("dp", "tp"), None)),
             P(("dp", "tp"), None))
    g = spmd(dp2tp4_mesh, oracle,
             (P(None, ("dp", "tp")), P(("dp", "tp"), None)),
             P(("dp", "tp"), None))
    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4)


def test_gemm_rs_2d_four_outer_groups():
    """n_o = 4 > 2: outer puts span multiple hops — exercises the
    barrier_all entry path (neighbour barriers are insufficient)."""
    import numpy as np
    from jax.sharding import Mesh
    from triton_dist_tpu.parallel.mesh import MeshContext

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("dcn", "ici"))
    mctx = MeshContext.from_mesh(mesh)
    m, k, n_dim = 128, 32, 16
    a = _rand((m, k), 17)
    b = _rand((k, n_dim), 18)
    ctx = create_gemm_rs_context(mctx, axis=("dcn", "ici"),
                                 block_m=16, block_n=16, block_k=16)

    def oracle(x, w):
        partial = jnp.dot(x, w, preferred_element_type=jnp.float32)
        return jax.lax.psum_scatter(
            partial, ("dcn", "ici"), scatter_dimension=0,
            tiled=True).astype(x.dtype)

    f = spmd(mesh, lambda x, w: gemm_rs(x, w, ctx),
             (P(None, ("dcn", "ici")), P(("dcn", "ici"), None)),
             P(("dcn", "ici"), None))
    g = spmd(mesh, oracle,
             (P(None, ("dcn", "ici")), P(("dcn", "ici"), None)),
             P(("dcn", "ici"), None))
    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4)


def test_ag_gemm_pipelined_back_to_back(tp8_mesh, tp8_ctx):
    """Two pipelined calls in one program (the persistent-context usage
    the retired ``ws=`` threading existed for): the scoped-VMEM variant
    has no workspace init to amortize — back-to-back calls just work,
    each returning its own gathered A."""
    a1 = _rand((256, 32), 19)
    a2 = _rand((256, 32), 20)
    b = _rand((32, 64), 21)
    ctx = create_ag_gemm_context(tp8_ctx, block_m=16, block_n=8,
                                 variant="pipelined")

    def two_calls(x1, x2, w):
        o1, ag1 = ag_gemm(x1, w, ctx, return_ag=True)
        o2, ag2 = ag_gemm(x2, w, ctx, return_ag=True)
        return o1, o2, ag1, ag2

    f = spmd(tp8_mesh, two_calls,
             (P("tp", None), P("tp", None), P(None, "tp")),
             (P(None, "tp"), P(None, "tp"), P(None, None),
              P(None, None)))
    o1, o2, ag1, ag2 = f(a1, a2, b)
    g = spmd(tp8_mesh, lambda x, w: ag_gemm_ref(x, w),
             (P("tp", None), P(None, "tp")), P(None, "tp"))
    assert_allclose(o1, g(a1, b), rtol=1e-4, atol=1e-4)
    assert_allclose(o2, g(a2, b), rtol=1e-4, atol=1e-4)
    assert_allclose(ag1, a1)
    assert_allclose(ag2, a2)


def test_ag_gemm_pipelined_sim_runs_real_kernel(monkeypatch):
    """Regression for the deleted interpret fallback: variant=
    "pipelined" under sim-ranks must dispatch the REAL pipelined
    kernel (the old aliased form silently rewrote itself to "panel"
    under interpret, so the sim parity sweep never tested it)."""
    import importlib

    import numpy as np
    from jax.sharding import Mesh
    from triton_dist_tpu.parallel.mesh import MeshContext

    # the ops package re-exports the ag_gemm FUNCTION under the same
    # name, so attribute imports shadow the module
    mod = importlib.import_module("triton_dist_tpu.ops.ag_gemm")
    calls = []
    real = mod._ag_gemm_pipelined

    def spy(*args, **kw):
        calls.append(True)
        return real(*args, **kw)

    monkeypatch.setattr(mod, "_ag_gemm_pipelined", spy)
    mesh1 = Mesh(np.array(jax.devices()[:1]), ("tp",))
    ctx1 = MeshContext.from_mesh(mesh1)
    a = _rand((256, 32), 60)
    b = _rand((32, 64), 61)
    ctx = create_ag_gemm_context(ctx1, block_m=16, block_n=8,
                                 variant="pipelined")
    f = spmd(mesh1, lambda x, w: ag_gemm(x, w, ctx, sim_ranks=4),
             (P(None, None), P(None, None)), P(None, None))
    assert_allclose(f(a, b), jnp.dot(a, b), rtol=1e-4, atol=1e-4)
    assert calls, ("pipelined variant fell back off the real kernel "
                   "under sim-ranks interpret")


def test_gemm_ar_2d(dp2tp4_mesh, dp2tp4_ctx):
    """Hierarchical GEMM+AR: fused inner-axis kernel + one outer
    exchange vs the two-axis psum oracle."""
    m, k, n_dim = 16, 128, 64
    a = _rand((m, k), 22)
    b = _rand((k, n_dim), 23)
    ctx = create_gemm_ar_context(dp2tp4_ctx, axis=("dp", "tp"),
                                 block_n=32)

    def oracle(x, w):
        p = jnp.dot(x, w, preferred_element_type=jnp.float32)
        return jax.lax.psum(p, ("dp", "tp")).astype(x.dtype)

    f = spmd(dp2tp4_mesh, lambda x, w: gemm_ar(x, w, ctx),
             (P(None, ("dp", "tp")), P(("dp", "tp"), None)),
             P(None, None))
    g = spmd(dp2tp4_mesh, oracle,
             (P(None, ("dp", "tp")), P(("dp", "tp"), None)),
             P(None, None))
    assert_allclose(f(a, b), g(a, b), rtol=1e-4, atol=1e-4)
