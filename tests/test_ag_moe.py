"""AG-MoE: sorted-layout prep, Pallas grouped GEMM, fused AG+grouped GEMM.

Oracle pattern per SURVEY.md §4: XLA collective + einsum vs the fused
kernel (the reference checks ``ag_group_gemm`` against torch allgather +
per-expert matmul in ``test/nvidia/test_ag_group_gemm.py``-style
scripts).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.ag_moe import (
    ag_group_gemm, ag_moe_ref, create_ag_moe_context,
    prepare_grouped_tokens,
)
from triton_dist_tpu.ops.group_gemm import (
    grouped_gemm, grouped_gemm_tiles, sort_by_expert,
)
from triton_dist_tpu.utils.testing import spmd


def test_prepare_grouped_tokens_roundtrip():
    t, d, e, k, tm = 24, 16, 4, 2, 8
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (t, d), jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(1), (t, k), 0, e)
    x_sorted, tile_expert, row_src = prepare_grouped_tokens(x, ids, e, tm)

    assert x_sorted.shape[0] % tm == 0
    x_sorted, tile_expert, row_src = map(np.asarray,
                                         (x_sorted, tile_expert, row_src))
    flat = np.asarray(ids).reshape(-1)
    x_rep = np.repeat(np.asarray(x), k, axis=0)
    # Every (token, k) assignment appears exactly once, in its expert's
    # tile-aligned segment; padding rows are zero and marked -1.
    seen = np.zeros(t * k, bool)
    for r, src in enumerate(row_src):
        if src < 0:
            np.testing.assert_array_equal(x_sorted[r], 0)
            continue
        assert not seen[src]
        seen[src] = True
        np.testing.assert_array_equal(x_sorted[r], x_rep[src])
        assert tile_expert[r // tm] == flat[src]
    assert seen.all()
    # Expert-major: expert ids along used tiles are non-decreasing.
    used = sorted(set(r // tm for r in range(len(row_src))
                      if row_src[r] >= 0))
    exps = [tile_expert[u] for u in used]
    assert exps == sorted(exps)


def test_grouped_gemm_tiles_matches_ragged_dot():
    t, d, f, e, k, tm = 32, 32, 48, 4, 2, 8
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (t, d), jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(3), (t, k), 0, e)
    w = jax.random.normal(jax.random.PRNGKey(4), (e, d, f)) * d ** -0.5

    x_sorted, tile_expert, row_src = prepare_grouped_tokens(x, ids, e, tm)
    out = grouped_gemm_tiles(x_sorted, w, tile_expert, block_n=16,
                             block_k=16)

    # Oracle: ragged_dot over the unpadded sort.
    x_rep = jnp.repeat(x, k, axis=0)
    srt, sizes, inv = sort_by_expert(x_rep, ids.reshape(-1), e)
    want = grouped_gemm(srt, w, sizes)[inv]     # flat (t*k, f) order
    got = np.asarray(out)[np.asarray(row_src) >= 0]
    # Rows of `out` in row_src order == flat order after selecting valid.
    order = np.asarray(row_src)[np.asarray(row_src) >= 0]
    restored = np.empty_like(got)
    restored[order] = got
    np.testing.assert_allclose(restored, np.asarray(want), rtol=1e-5,
                               atol=1e-5)


@pytest.mark.parametrize("block_m", [8, 16])
def test_ag_group_gemm_vs_ref(tp8_mesh, tp8_ctx, block_m):
    n = 8
    t_loc, d, f_loc, e, k = 16, 32, 32, 4, 2
    keys = jax.random.split(jax.random.PRNGKey(5), 3)
    x = jax.random.normal(keys[0], (n * t_loc, d), jnp.float32)
    ids = jax.random.randint(keys[1], (n * t_loc, k), 0, e)
    w = jax.random.normal(keys[2], (e, d, f_loc)) * d ** -0.5

    ctx = create_ag_moe_context(tp8_ctx, num_experts=e, block_m=block_m,
                                block_n=16, block_k=16)

    def prep(x_loc, ids_loc):
        return prepare_grouped_tokens(x_loc, ids_loc, e, block_m)

    x_s, te, row_src = spmd(
        tp8_mesh, prep, (P("tp", None), P("tp", None)),
        (P("tp", None), P("tp"), P("tp")))(x, ids)

    got = spmd(
        tp8_mesh, functools.partial(ag_group_gemm, ctx=ctx),
        (P("tp", None), P(None, None, None), P("tp")),
        P(None, None))(x_s, w, te)

    want = spmd(
        tp8_mesh, ag_moe_ref,
        (P("tp", None), P(None, None, None), P("tp")),
        P(None, None))(x_s, w, te)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    # And the ref itself equals a dense per-row matmul on valid rows.
    x_full = np.asarray(x_s).reshape(-1, d)
    src_all = np.asarray(row_src).reshape(n, -1)
    w_np = np.asarray(w)
    ids_np = np.asarray(ids).reshape(n, t_loc * k)
    got_np = np.asarray(got)
    s_loc = x_s.shape[0] // n
    for c in range(n):
        for r in range(s_loc):
            src = src_all[c, r]
            if src < 0:
                continue
            eid = ids_np[c, src]
            np.testing.assert_allclose(
                got_np[c * s_loc + r],
                x_full[c * s_loc + r] @ w_np[eid], rtol=1e-4, atol=1e-4)
