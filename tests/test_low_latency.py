"""Low-latency AG family + slot-parity quantized A2A tests.

Reference test pattern: ``test/nvidia/test_low_latency_allgather.py``
and ``test_low_latency_all_to_all.py`` (torch allclose oracles).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.all_to_all import all_to_all_ref
from triton_dist_tpu.ops.allgather import all_gather_ref
from triton_dist_tpu.ops.low_latency import (
    _factor, fast_allgather, ll_a2a,
)
from triton_dist_tpu.utils.testing import spmd, assert_allclose


def _rand(shape, seed):
    return jax.random.normal(jax.random.PRNGKey(seed), shape)


def test_factorization():
    assert sorted(_factor(8, 2)) == [2, 4]
    assert _factor(8, 3) == (2, 2, 2)
    assert np.prod(_factor(12, 2)) == 12
    assert np.prod(_factor(7, 3)) == 7  # degenerate dims of 1 allowed


@pytest.mark.parametrize("mode", ["push_1d", "push_2d", "push_3d"])
def test_fast_allgather_modes(tp8_mesh, tp8_ctx, mode):
    """Every push schedule equals lax.all_gather (small decode-shape
    message)."""
    x = _rand((8, 64), 1)
    f = spmd(tp8_mesh,
             lambda v: fast_allgather(v, ctx=tp8_ctx, axis="tp",
                                      mode=mode),
             P("tp", None), P(None, None))
    g = spmd(tp8_mesh, lambda v: all_gather_ref(v, axis="tp"),
             P("tp", None), P(None, None))
    assert_allclose(f(x), g(x))


def test_fast_allgather_pull_raises(tp8_ctx):
    with pytest.raises(NotImplementedError):
        fast_allgather(jnp.ones((8, 8)), ctx=tp8_ctx, axis="tp",
                       mode="pull")


def test_ll_a2a_quantized(tp8_mesh, tp8_ctx):
    """In-kernel int8 wire quant: matches the XLA a2a within quant
    tolerance."""
    x = _rand((64, 4, 32), 2)  # per shard (8, 4, 32)
    f = spmd(tp8_mesh,
             lambda v: ll_a2a(v, ctx=tp8_ctx, axis="tp", step=0),
             P("tp", None, None), P("tp", None, None))
    g = spmd(tp8_mesh, lambda v: all_to_all_ref(v, axis="tp"),
             P("tp", None, None), P("tp", None, None))
    got, want = np.asarray(f(x)), np.asarray(g(x))
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)


def test_ll_a2a_back_to_back_slots(tp8_mesh, tp8_ctx):
    """Aliasing regression (advisor r1 / reference v2 double-buffer):
    two consecutive decode-step calls — opposite slot parities — inside
    ONE jit must both be correct."""
    x = _rand((64, 4, 32), 3)

    def two_steps(v):
        a = ll_a2a(v, ctx=tp8_ctx, axis="tp", step=0)
        b = ll_a2a(a, ctx=tp8_ctx, axis="tp", step=1)
        return b

    f = spmd(tp8_mesh, two_steps, P("tp", None, None),
             P("tp", None, None))
    # a2a twice with routing by-source both times is NOT identity; the
    # oracle is the same composition in XLA.
    g = spmd(tp8_mesh,
             lambda v: all_to_all_ref(all_to_all_ref(v, axis="tp"),
                                      axis="tp"),
             P("tp", None, None), P("tp", None, None))
    got, want = np.asarray(f(x)), np.asarray(g(x))
    # Two quantization round-trips: ~2x the single-step budget.
    np.testing.assert_allclose(got, want, rtol=0.1, atol=0.1)


def test_ll_a2a_single_rank_wire_roundtrip():
    """n == 1 short-circuit still applies the wire round-trip so
    numerics match the distributed path."""
    from triton_dist_tpu.parallel.mesh import MeshContext
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    ctx = MeshContext.from_mesh(mesh)
    x = _rand((1, 4, 32), 4)
    out = ll_a2a(x, ctx=ctx, axis="tp", step=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x),
                               rtol=0.05, atol=0.05)


def test_ll_a2a_steps_matches_single_steps(tp8_mesh, tp8_ctx):
    """The multi-step in-kernel loop (one entry barrier, slot-parity
    wire buffers, credit flow control) must match S independent
    single-step calls bit-for-bit."""
    from triton_dist_tpu.ops import ll_a2a, ll_a2a_steps

    S, c, d = 5, 4, 32
    xs = jax.random.normal(jax.random.PRNGKey(70), (S, 64, c, d),
                           jnp.float32)

    f = spmd(tp8_mesh,
             lambda v: ll_a2a_steps(v, ctx=tp8_ctx, axis="tp"),
             P(None, "tp", None, None), P(None, "tp", None, None))
    got = np.asarray(f(xs))

    for s in range(S):
        g = spmd(tp8_mesh,
                 lambda v, s=s: ll_a2a(v, ctx=tp8_ctx, axis="tp",
                                       step=s),
                 P("tp", None, None), P("tp", None, None))
        want = np.asarray(g(xs[s]))
        np.testing.assert_array_equal(got[s], want)


def test_ll_a2a_steps_two_steps_credit_balance(tp8_mesh, tp8_ctx):
    """S == 2: no credits are ever granted or waited (both steps are in
    the warm-up window) — the kernel must still drain cleanly."""
    from triton_dist_tpu.ops import ll_a2a_steps

    xs = jax.random.normal(jax.random.PRNGKey(71), (2, 64, 4, 32),
                           jnp.float32)
    f = spmd(tp8_mesh,
             lambda v: ll_a2a_steps(v, ctx=tp8_ctx, axis="tp"),
             P(None, "tp", None, None), P(None, "tp", None, None))
    out = np.asarray(f(xs))
    assert np.isfinite(out).all()


def test_ll_a2a_hardware_scales_layout(tp8_mesh, tp8_ctx):
    """Force the HARDWARE lane-aligned (width-128) scales layout under
    interpret mode — the interpret/silicon divergence point must be
    CPU-testable (VERDICT r4 weak #3)."""
    from triton_dist_tpu.ops import ll_a2a, low_latency

    x = _rand((64, 2, 32), 80)
    prev = low_latency._SCALE_WIDTH_OVERRIDE
    low_latency._SCALE_WIDTH_OVERRIDE = 128
    try:
        f = spmd(tp8_mesh,
                 lambda v: ll_a2a(v, ctx=tp8_ctx, axis="tp", step=0),
                 P("tp", None, None), P("tp", None, None))
        got = np.asarray(f(x))
    finally:
        low_latency._SCALE_WIDTH_OVERRIDE = prev
    g = spmd(tp8_mesh, lambda v: all_to_all_ref(v, axis="tp"),
             P("tp", None, None), P("tp", None, None))
    want = np.asarray(g(x))
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)
