"""Process-level fault domain battery (docs/resilience.md, "Process
supervision" / "Payload integrity"): the ServingSupervisor's
crash/stall recovery with token-exact stream resume, the journaled
checkpoint ring incl. corrupt-newest fallback, the parent-side ack
dedupe protocol, end-to-end payload-integrity detection at every
serialization boundary, and the supervised chaos soak (slow).

The subprocess tests spawn REAL children (the tiny-model factory in
``chaos.supervised_tiny_factory``) — each spawn pays a JAX import +
compile, so they share one module-scoped checkpoint-dir tree and keep
streams short.  Everything parent-protocol-level (dedupe, ring walk,
envelope) runs in-process and is fast.
"""

import os
import time

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from triton_dist_tpu.models import Engine, ModelConfig
from triton_dist_tpu.resilience import chaos
from triton_dist_tpu.resilience.integrity import (
    CheckpointCorruptError, IntegrityError, payload_digest,
    verify_payload)
from triton_dist_tpu.resilience.supervisor import (
    CheckpointRing, ServingSupervisor, SupervisedHandle,
    SupervisorProtocolError)
from triton_dist_tpu.serving import FleetRouter, Request, ServingEngine
from triton_dist_tpu.serving.server import (
    load_checkpoint, save_checkpoint)

FACTORY = "triton_dist_tpu.resilience.chaos:supervised_tiny_factory"

CFG = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                       intermediate_size=32, num_hidden_layers=2,
                       num_attention_heads=4, num_key_value_heads=4,
                       head_dim=8)


@pytest.fixture(scope="module")
def engine():
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    return Engine(CFG, mesh, mode="xla", max_len=32, seed=0)


def _oracle(engine, prompt, gen):
    import jax.numpy as jnp
    ids = jnp.asarray(np.asarray([list(prompt)], np.int32))
    return np.asarray(engine.serve(ids, gen_len=gen))[0].tolist()


def _wait(sup, pred, *, deadline_s=240.0, what="condition"):
    t0 = time.monotonic()
    while not pred():
        sup.pump()
        if time.monotonic() - t0 > deadline_s:
            raise TimeoutError(
                f"{what} not reached in {deadline_s}s "
                f"(stats={sup.stats()})")
        time.sleep(0.02)


# ---------------------------------------------------------------------------
# Checkpoint envelope hardening (in-process)
# ---------------------------------------------------------------------------

def test_checkpoint_envelope_detects_bit_flip(tmp_path):
    """A flipped byte anywhere in the checkpoint file surfaces as
    CheckpointCorruptError — never a raw pickle traceback."""
    path = str(tmp_path / "snap.pkl")
    save_checkpoint({"anything": [1, 2, 3]}, path)
    assert load_checkpoint(path) == {"anything": [1, 2, 3]}
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0x40
    open(path, "wb").write(bytes(raw))
    with pytest.raises(CheckpointCorruptError) as ei:
        load_checkpoint(path)
    assert ei.value.path == path


def test_checkpoint_envelope_detects_truncation(tmp_path):
    path = str(tmp_path / "snap.pkl")
    save_checkpoint({"x": list(range(100))}, path)
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[: len(raw) // 2])
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(path)
    # Absence is NOT corruption — callers distinguish the two.
    with pytest.raises(FileNotFoundError):
        load_checkpoint(str(tmp_path / "never-written.pkl"))


def test_checkpoint_ring_prunes_and_orders(tmp_path):
    ring = CheckpointRing(str(tmp_path), keep=2)
    p0 = ring.append({"n": 0}, tick=1)
    p1 = ring.append({"n": 1}, tick=2)
    p2 = ring.append({"n": 2}, tick=3)
    assert not os.path.exists(p0)          # pruned past keep
    ents = ring.entries()
    assert [e["seq"] for e in ents] == [2, 1]   # newest first
    assert ring.newest_good() == p2
    assert load_checkpoint(p1) == {"n": 1}


def test_ring_corrupt_newest_falls_back_to_predecessor(tmp_path):
    """The restore walk skips a corrupted newest snapshot and lands on
    its ring predecessor (the supervisor's restore_fallbacks path)."""
    ring = CheckpointRing(str(tmp_path), keep=3)
    ring.append({"n": 0}, tick=1)
    p1 = ring.append({"n": 1}, tick=2)
    p2 = ring.append({"n": 2}, tick=3)
    raw = bytearray(open(p2, "rb").read())
    raw[-3] ^= 0x01
    open(p2, "wb").write(bytes(raw))
    skipped = []
    assert ring.newest_good(
        on_fallback=lambda p, e: skipped.append((p, type(e)))) == p1
    assert skipped == [(p2, CheckpointCorruptError)]
    # All corrupt -> None (the supervisor then restarts from scratch).
    # A different byte than above — re-XORing the same bit on the
    # already-corrupt newest would RESTORE it.
    for ent in ring.entries():
        p = os.path.join(str(tmp_path), ent["file"])
        raw = bytearray(open(p, "rb").read())
        raw[10] ^= 0x80
        open(p, "wb").write(bytes(raw))
    assert ring.newest_good() is None


# ---------------------------------------------------------------------------
# Ack dedupe protocol (in-process: pure parent logic)
# ---------------------------------------------------------------------------

def _parent_only(tmp_path) -> ServingSupervisor:
    sup = ServingSupervisor(FACTORY, checkpoint_dir=str(tmp_path))
    h = SupervisedHandle("r1", [1, 2], {"max_new_tokens": 4},
                         stream_cb=None)
    sup.handles["r1"] = h
    sup._order.append("r1")
    return sup


def test_ack_dedupe_never_double_emits(tmp_path):
    """A restored child re-emits its FULL token history; the parent
    must fire the client callback exactly once per index no matter how
    many times an index is replayed."""
    sup = _parent_only(tmp_path)
    seen = []
    sup.handles["r1"].stream_cb = seen.append
    for i, tok in enumerate([7, 8, 9]):
        sup._on_tok("r1", i, tok)
    # Full-history replay after a simulated restart.
    for i, tok in enumerate([7, 8, 9]):
        sup._on_tok("r1", i, tok)
    sup._on_tok("r1", 3, 11)
    assert seen == [7, 8, 9, 11]
    assert sup.handles["r1"].tokens == [7, 8, 9, 11]
    assert sup.counters["dedup_dropped"] == 3
    assert sup.counters["acked_tokens"] == 4


def test_ack_replay_divergence_raises(tmp_path):
    """A replayed index carrying a DIFFERENT token is a divergence bug
    — the parent raises instead of silently re-emitting."""
    sup = _parent_only(tmp_path)
    sup._on_tok("r1", 0, 7)
    with pytest.raises(SupervisorProtocolError, match="diverged"):
        sup._on_tok("r1", 0, 8)


def test_ack_gap_raises(tmp_path):
    """Acks flush before the checkpoint containing them is written, so
    a restored child can never legitimately skip ahead — a gap is a
    protocol bug."""
    sup = _parent_only(tmp_path)
    sup._on_tok("r1", 0, 7)
    with pytest.raises(SupervisorProtocolError, match="gap"):
        sup._on_tok("r1", 2, 9)


# ---------------------------------------------------------------------------
# Live-child recovery (subprocess)
# ---------------------------------------------------------------------------

def test_crash_mid_decode_resumes_token_exact(tmp_path, engine):
    """SIGKILL the child mid-decode: the parent restores the newest
    ring snapshot into a fresh child and the client stream resumes
    token-exact with no double emission (docs/resilience.md)."""
    sup = ServingSupervisor(
        FACTORY, checkpoint_dir=str(tmp_path / "ring"),
        heartbeat_timeout_s=120.0, checkpoint_every=2,
        tick_throttle_s=0.05)
    seen = []
    with sup:
        h = sup.submit([3, 1, 2], max_new_tokens=12,
                       stream_cb=seen.append)
        _wait(sup, lambda: sup.counters["acked_tokens"] >= 3,
              what="3 acked tokens")
        sup.kill_child()
        sup.run_until_done(deadline_s=240)
        st = sup.stats()
    want = _oracle(engine, [3, 1, 2], 12)
    assert h.status == "done"
    assert h.tokens == want
    assert seen == want                      # exactly-once delivery
    assert st["crashes"] == 1 and st["restarts"] == 1
    assert st["checkpoints"] >= 1
    assert st["last_recovery_ms"] is not None


def test_stall_detection_kills_and_restores(tmp_path, engine):
    """A child that stops heartbeating (wedged thread model) is
    detected by heartbeat silence, SIGKILLed, and restored — the
    in-flight stream still finishes token-exact."""
    sup = ServingSupervisor(
        FACTORY, checkpoint_dir=str(tmp_path / "ring"),
        heartbeat_timeout_s=120.0, checkpoint_every=2,
        tick_throttle_s=0.05)
    with sup:
        h = sup.submit([5, 5, 5], max_new_tokens=10)
        # Warm first (compile gaps would false-trigger a tight
        # timeout), then tighten ONLY for the stall window.
        _wait(sup, lambda: sup.counters["acked_tokens"] >= 2,
              what="warm child")
        sup.heartbeat_timeout_s = 2.0
        sup.inject_stall()
        _wait(sup, lambda: sup.counters["stalls"] >= 1,
              deadline_s=60.0, what="stall detection")
        # Relax before the restored child's cold compile gap can
        # false-trigger again.
        sup.heartbeat_timeout_s = 120.0
        sup.run_until_done(deadline_s=240)
        st = sup.stats()
    assert h.status == "done"
    assert h.tokens == _oracle(engine, [5, 5, 5], 10)
    assert st["stalls"] == 1 and st["restarts"] == 1


def test_corrupt_newest_checkpoint_restores_ring_predecessor(
        tmp_path, engine):
    """Crash with a corrupted NEWEST snapshot: the parent's restore
    walk skips it (restore_fallbacks) and resumes from the ring
    predecessor — still token-exact."""
    ring_dir = str(tmp_path / "ring")
    sup = ServingSupervisor(
        FACTORY, checkpoint_dir=ring_dir, heartbeat_timeout_s=120.0,
        checkpoint_every=2, ring_k=3, tick_throttle_s=0.05)
    with sup:
        h = sup.submit([2, 4, 6], max_new_tokens=14)
        _wait(sup, lambda: sup.counters["checkpoints"] >= 2,
              what="two ring checkpoints")
        sup.kill_child()
        # Corrupt the newest snapshot ON DISK before the parent's
        # next pump runs recovery.
        newest = CheckpointRing(ring_dir).entries()[0]
        p = os.path.join(ring_dir, newest["file"])
        raw = bytearray(open(p, "rb").read())
        raw[len(raw) // 2] ^= 0x10
        open(p, "wb").write(bytes(raw))
        sup.run_until_done(deadline_s=240)
        st = sup.stats()
    assert h.status == "done"
    assert h.tokens == _oracle(engine, [2, 4, 6], 14)
    assert st["crashes"] == 1
    assert st["restore_fallbacks"] >= 1


# ---------------------------------------------------------------------------
# Payload integrity at every serialization boundary (in-process)
# ---------------------------------------------------------------------------

def test_payload_digest_detects_any_flip():
    a = np.arange(64, dtype=np.float32).reshape(8, 8)
    s = np.ones((4,), np.float32)
    d = payload_digest([a, s])
    assert verify_payload([a, s], d, boundary="unit") == d
    b = a.copy()
    b[3, 3] += 1e-6
    with pytest.raises(IntegrityError) as ei:
        verify_payload([b, s], d, boundary="unit", key="k1")
    assert ei.value.boundary == "unit" and ei.value.key == "k1"
    # Digest covers dtype/shape headers too, not just bytes.
    with pytest.raises(IntegrityError):
        verify_payload([a.reshape(4, 16), s], d, boundary="unit")
    # want=None is the pre-digest vacuous case.
    verify_payload([b, s], None, boundary="unit")


def test_integrity_drill_all_three_boundaries(engine):
    """Seeded corruption at tier-transfer, page-migration, and
    fleet-handoff: each is DETECTED (quarantine / integrity counters
    move) and RECOVERED token-exact — never a wrong token."""
    out = chaos.run_integrity_drill(engine)
    assert out["tier_quarantined"] >= 1
    assert out["migration_integrity_failures"] >= 1
    assert out["handoff_integrity_failures"] >= 1
    assert out["token_exact_requests"] == 3
    assert out["wrong_tokens"] == 0


def test_tier_corruption_quarantines_and_recomputes(engine):
    """Finer-grained than the drill: the corrupted tier entry is
    evicted (quarantined), the integrity span lands in telemetry, and
    the request recovers through the recompute path."""
    from triton_dist_tpu.resilience import faults

    srv = ServingEngine(engine, num_slots=2, page=4, num_pages=16,
                        prefix_reuse=True,
                        kv_tiers={"host_pages": 128},
                        telemetry="spans")
    h = srv.submit([5, 3, 5, 3, 5, 3], max_new_tokens=6)
    for _ in range(64):
        if h.status == "running" and h.tokens:
            break
        srv.step()
    srv.park(h)
    key = ("session", h.request.request_id)
    assert key in srv.tiers
    srv.resume(h)
    plan = faults.get_plan("corrupt_payload", op="tier_transfer",
                           k=None)
    with faults.inject(plan):
        srv.step()
    assert key not in srv.tiers              # quarantined, not served
    assert srv.tiers.stats_counters["integrity_quarantined"] >= 1
    assert srv.stats_counters["integrity_failures"] >= 1
    kinds = [s.kind for s in srv.obs.log.spans()]
    assert "integrity_check" in kinds
    srv.run()
    assert h.status == "done"
    assert h.tokens == _oracle(engine, [5, 3, 5, 3, 5, 3], 6)


# ---------------------------------------------------------------------------
# Satellite: one injectable clock across the fleet topology
# ---------------------------------------------------------------------------

def test_fleet_router_single_injectable_clock(engine):
    """The router's clock governs EVERY fleet's scheduler and
    telemetry — including fleets added by scale_to — so a fake clock
    drives deadline expiry deterministically across the topology."""
    t = {"now": 100.0}

    def clock():
        return t["now"]

    def factory():
        return ServingEngine(engine, num_slots=2, page=4,
                             num_pages=16, prefix_reuse=True)

    router = FleetRouter(factory, fleets=2, clock=clock)
    router.scale_to(3)
    for f in router.fleets:
        assert f.engine.sched.clock is clock
        assert f.engine.obs.clock is clock
    h = router.submit(Request(prompt=[1, 2], max_new_tokens=4,
                              deadline=105.0))
    router.step()
    assert not h.done
    t["now"] = 106.0                       # fake time passes; no wall
    for _ in range(4):
        router.step()
    assert h.status == "timeout"


# ---------------------------------------------------------------------------
# The supervised soak (slow: several real child lifecycles)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_supervised_soak_survives_kills_and_stalls(tmp_path):
    """The acceptance soak: >= 6 seeded child kills/stalls in one run,
    every finished stream token-exact vs the in-process oracle."""
    rep = chaos.run_supervised_soak(
        checkpoint_dir=str(tmp_path / "ring"), seed=7, n_requests=8,
        n_faults=6, kinds=chaos.SUPERVISED_FAULT_KINDS[:3],
        deadline_s=480.0)
    assert rep.survived_faults >= 6
    assert rep.requests["done"] == rep.requests["submitted"] == 8
    assert rep.token_exact_requests == 8
    assert rep.supervisor["restarts"] >= 1


def test_supervised_mini_soak(tmp_path):
    """Tier-1 mini soak: a short seeded schedule with one hard kill —
    the cheap always-on cousin of the slow acceptance soak."""
    rep = chaos.run_supervised_soak(
        checkpoint_dir=str(tmp_path / "ring"), seed=11, n_requests=3,
        n_faults=2, kinds=(("kill_child", None, None),),
        gen_choices=(4, 6), deadline_s=300.0)
    assert rep.survived_faults >= 1
    assert rep.requests["done"] == 3
    assert rep.token_exact_requests == 3
