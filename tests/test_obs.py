"""Observability battery: span timelines, latency histograms, and the
merged Perfetto export.

Everything timeline-shaped runs under an injected fake clock (the
scheduler's clock IS the telemetry clock), so span orderings and
TTFT/ITL values are deterministic. The bit-exactness block is the
subsystem's core contract: telemetry="spans" is pure host-side
bookkeeping — token outputs and every jit no-growth gate are identical
to telemetry="off".
"""

import json
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from triton_dist_tpu.models import Engine, ModelConfig, dense
from triton_dist_tpu.obs import (
    SPAN_KINDS, EventLog, HistogramSet, LatencyHistogram, Span,
    Telemetry,
)
from triton_dist_tpu.resilience import chaos, faults
from triton_dist_tpu.resilience.policy import RetryPolicy
from triton_dist_tpu.resilience.watchdog import HealthTracker
from triton_dist_tpu.serving import DisaggServingEngine, ServingEngine

CFG = ModelConfig.tiny()
MAX_LEN = 64
PAGE = 8
TP = 4


@pytest.fixture(scope="module")
def engine():
    mesh = Mesh(np.array(jax.devices()[:TP]), ("tp",))
    return Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=3)


@pytest.fixture(scope="module")
def role_engines():
    params = dense.init_params(jax.random.PRNGKey(3), CFG)
    devs = jax.devices()
    pf = Engine(CFG, Mesh(np.array(devs[:2]), ("tp",)), mode="xla",
                max_len=MAX_LEN, params=params)
    dec = Engine(CFG, Mesh(np.array(devs[2:4]), ("tp",)), mode="xla",
                 max_len=MAX_LEN, params=params)
    return pf, dec


def _kinds(srv, request_id=None):
    """Ordered span kinds from the engine's event log (optionally
    filtered to one request's timeline)."""
    return [s.kind for s in srv.obs.log.spans()
            if request_id is None or s.request_id == request_id]


# ---------------------------------------------------------------------------
# Histogram bucket math + percentile summaries (pure host units)
# ---------------------------------------------------------------------------

def test_histogram_bucket_boundaries_geometric():
    h = LatencyHistogram(lo=1e-3, hi=1e3, buckets_per_decade=6)
    ratios = [b2 / b1 for b1, b2 in zip(h.bounds, h.bounds[1:])]
    assert all(abs(r - h.ratio) < 1e-9 for r in ratios)
    assert abs(h.bounds[0] - 1e-3) < 1e-12
    assert abs(h.bounds[-1] - 1e3) < 1e-9
    # 6 decades x 6 buckets/decade = 36 buckets -> 37 bounds.
    assert len(h.bounds) == 37


def test_histogram_bucket_index_edges():
    h = LatencyHistogram(lo=1e-3, hi=1e3, buckets_per_decade=6)
    assert h.bucket_index(1e-4) == 0          # underflow
    assert h.bucket_index(1e-3) == 1          # exactly lo -> bucket 1
    assert h.bucket_index(2e3) == len(h.bounds)   # overflow
    # A value inside bucket i sits in [bounds[i-1], bounds[i]).
    for v in (0.002, 0.5, 7.0, 999.0):
        i = h.bucket_index(v)
        assert h.bounds[i - 1] <= v < h.bounds[i]


def test_histogram_percentiles_bounded_relative_error():
    h = LatencyHistogram()
    vals = [0.001 * (1.3 ** i) for i in range(40)]   # 1ms .. ~36s
    for v in vals:
        h.observe(v)
    s = h.summary()
    assert s["count"] == 40
    exact = sorted(vals)
    for q, key in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
        want = exact[max(0, math.ceil(q * 40) - 1)] * 1e3
        got = s[key]
        assert want / h.ratio <= got <= want * h.ratio, (
            f"{key}: {got} vs exact {want} (ratio {h.ratio})")
    assert s["min"] == pytest.approx(min(vals) * 1e3, rel=1e-6)
    assert s["max"] == pytest.approx(max(vals) * 1e3, rel=1e-6)
    assert s["mean"] == pytest.approx(
        sum(vals) / 40 * 1e3, rel=1e-4)


def test_histogram_single_value_clamped():
    h = LatencyHistogram()
    h.observe(0.0075)
    s = h.summary()
    # The bucket midpoint is clamped to the observed min/max, so a
    # 1-sample histogram answers exactly.
    assert s["p50"] == s["p99"] == pytest.approx(7.5, rel=1e-6)
    assert h.summary()["count"] == 1
    assert LatencyHistogram().summary() is None


def test_histogram_set_tenant_grouping():
    hs = HistogramSet()
    hs.observe("ttft", 0.010, tenant="a")
    hs.observe("ttft", 0.020, tenant="b")
    hs.observe("ttft", 0.030)                 # untagged
    s = hs.summary()
    assert s["ttft"]["count"] == 3, "aggregate counts every observation"
    assert s["per_tenant"]["a"]["ttft"]["count"] == 1
    assert s["per_tenant"]["b"]["ttft"]["count"] == 1


# ---------------------------------------------------------------------------
# Event ring + JSONL round-trip
# ---------------------------------------------------------------------------

def test_event_log_ring_bounding():
    log = EventLog(capacity=8)
    for i in range(20):
        log.append(Span(kind="submit", t0=float(i)))
    assert len(log) == 8 and log.total == 20 and log.dropped == 12
    assert [s.t0 for s in log.spans()] == [float(i) for i in
                                           range(12, 20)]


def test_event_log_jsonl_roundtrip(tmp_path):
    log = EventLog(capacity=16)
    log.append(Span(kind="decode", t0=1.0, t1=2.5, step=3,
                    attrs={"batch": 2}))
    log.append(Span(kind="retry", t0=3.0, request_id="req-1",
                    slot=1, tenant="t0", attrs={"op": "x"}))
    p = log.to_jsonl(str(tmp_path / "log.jsonl"))
    back = EventLog.from_jsonl(p)
    assert [s.to_dict() for s in back.spans()] == [
        s.to_dict() for s in log.spans()]
    # and the lines are plain JSON (one span per line)
    lines = open(p).read().splitlines()
    assert len(lines) == 2 and json.loads(lines[0])["kind"] == "decode"


def test_span_taxonomy_well_formed():
    assert len(set(SPAN_KINDS)) == len(SPAN_KINDS)
    for k in ("queue_wait", "prefill_chunk", "migration", "decode",
              "spec_verify", "retry", "failover", "preempt",
              "checkpoint", "restore", "chaos_fault"):
        assert k in SPAN_KINDS


# ---------------------------------------------------------------------------
# Telemetry facade modes
# ---------------------------------------------------------------------------

def test_telemetry_mode_gating():
    t = [0.0]
    off = Telemetry("off", clock=lambda: t[0])
    with off.span("decode"):
        t[0] += 1.0
    off.event("retry")
    off.observe("ttft", 1.0)
    assert off.latency_summary() is None and len(off.log) == 0

    cnt = Telemetry("counters", clock=lambda: t[0])
    with cnt.span("decode"):
        t[0] += 2.0
    cnt.event("retry")
    assert len(cnt.log) == 0, "counters mode allocates no spans"
    s = cnt.latency_summary()
    assert s["ops"]["decode"]["count"] == 1
    assert s["ops"]["decode"]["min"] == pytest.approx(2000.0)
    assert s["counters"]["retry"] == 1

    sp = Telemetry("spans", clock=lambda: t[0])
    with sp.span("decode", step=7):
        t[0] += 1.0
    sp.event("retry", op="migration")
    spans = sp.log.spans()
    assert [x.kind for x in spans] == ["decode", "retry"]
    assert spans[0].step == 7 and spans[0].duration == 1.0
    assert spans[1].instant and spans[1].attrs["op"] == "migration"
    with pytest.raises(ValueError):
        Telemetry("verbose")


def test_span_records_error_kind():
    sp = Telemetry("spans")
    with pytest.raises(RuntimeError):
        with sp.span("migration"):
            raise RuntimeError("boom")
    (s,) = sp.log.spans()
    assert s.attrs["error"] == "RuntimeError"


# ---------------------------------------------------------------------------
# Deterministic serving timelines under a fake clock
# ---------------------------------------------------------------------------

def test_request_timeline_ordering_and_ttft(engine):
    t = [10.0]
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        telemetry="spans", clock=lambda: t[0])
    h = srv.submit([1, 2, 3], max_new_tokens=3, tenant="acme")
    t[0] = 12.0
    srv.run()
    ks = _kinds(srv)
    # Lifecycle ordering: submit -> queue_wait -> admit -> prefill ->
    # first_token -> decode... -> request(terminal).
    for a, b in (("submit", "queue_wait"), ("queue_wait", "admit"),
                 ("admit", "prefill"), ("prefill", "first_token"),
                 ("first_token", "decode"), ("decode", "request")):
        assert ks.index(a) < ks.index(b), ks
    by_kind = {s.kind: s for s in srv.obs.log.spans()}
    qw = by_kind["queue_wait"]
    assert (qw.t0, qw.t1) == (10.0, 12.0)
    assert qw.request_id == h.request.request_id
    assert qw.tenant == "acme"
    req = by_kind["request"]
    assert req.attrs["status"] == "done"
    assert req.attrs["tokens"] == 3
    # TTFT on the fake clock: submit at 10, first token at 12 -> 2s,
    # exact in the histogram's min/max fields.
    lat = srv.stats()["latency"]
    assert lat["ttft_ms"]["count"] == 1
    assert lat["ttft_ms"]["min"] == pytest.approx(2000.0)
    assert lat["per_tenant"]["acme"]["ttft_ms"]["count"] == 1


def test_chunked_prefill_timeline(engine):
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        prefill_buckets=(4, 8), telemetry="spans",
                        clock=lambda: 0.0)
    h = srv.submit(list(range(1, 11)), max_new_tokens=2)
    srv.run()
    ks = _kinds(srv, h.request.request_id)
    chunk_spans = [s for s in srv.obs.log.spans()
                   if s.kind == "prefill_chunk"]
    # 10 tokens over (4, 8) buckets: plan_chunks covers it in >= 2
    # chunks, each span carrying its (start, bucket, valid) triple.
    assert len(chunk_spans) == len(h.chunks) >= 2
    assert [(s.attrs["start"], s.attrs["bucket"], s.attrs["valid"])
            for s in chunk_spans] == [tuple(c) for c in h.chunks]
    assert "prefill" not in ks, "chunked admission has no monolithic span"
    assert ks.index("prefill_chunk") < ks.index("first_token")
    # per-bucket counters from the chunk driver
    counters = srv.stats()["latency"]["counters"]
    assert sum(v for k, v in counters.items()
               if k.startswith("chunk_bucket_")) == len(chunk_spans)


def test_disagg_migration_timeline(role_engines):
    pf, dec = role_engines
    srv = DisaggServingEngine(dec, prefill_engine=pf, num_slots=2,
                              page=PAGE, prefill_buckets=(4, 16),
                              telemetry="spans", clock=lambda: 0.0)
    h = srv.submit([5, 6, 7, 8, 9], max_new_tokens=2)
    srv.run()
    ks = _kinds(srv)
    assert "migration" in ks and "prefill_chunk" in ks
    mig = next(s for s in srv.obs.log.spans() if s.kind == "migration")
    assert mig.request_id == h.request.request_id
    assert mig.attrs["pages"] >= 1
    assert mig.attrs["transport"] in ("local", "p2p")
    assert ks.index("prefill_chunk") < ks.index("migration")
    assert ks.index("migration") < ks.index("request")
    chaos.check_invariants(srv)


def test_spec_timeline_draft_verify_rollback(engine):
    srv = ServingEngine(engine, num_slots=2, page=PAGE, spec_k=4,
                        telemetry="spans", clock=lambda: 0.0)
    # A sampled request commits exactly one token per K-token dispatch
    # (greedy acceptance does not apply), so its rejected suffix rolls
    # back every tick — a deterministic rollback source. The greedy
    # companion exercises the n-gram proposer (sampled requests never
    # draft).
    h = srv.submit([1, 9, 4, 2], max_new_tokens=6, temperature=0.5,
                   seed=7)
    srv.submit([1, 2, 3, 1, 2, 3], max_new_tokens=4)
    srv.run()
    ks = _kinds(srv)
    assert "spec_draft" in ks and "spec_verify" in ks
    assert ks.index("spec_draft") < ks.index("spec_verify")
    verify = [s for s in srv.obs.log.spans() if s.kind == "spec_verify"]
    assert all(s.attrs["k"] == 4 for s in verify)
    rollbacks = [s for s in srv.obs.log.spans()
                 if s.kind == "spec_rollback"]
    assert rollbacks, "a mispredicting draft must roll back"
    assert all(s.attrs["accepted"] + s.attrs["rolled"] <= 4
               for s in rollbacks)
    # draft-quality counters from the n-gram proposer
    counters = srv.stats()["latency"]["counters"]
    assert any(k.startswith("draft_ngram_") for k in counters)
    assert h.status == "done"


def test_retry_events_interleave_with_attempt_spans(role_engines):
    pf, dec = role_engines
    srv = DisaggServingEngine(
        dec, prefill_engine=pf, num_slots=2, page=PAGE,
        prefill_buckets=(4, 16), retry=RetryPolicy(max_attempts=3),
        telemetry="spans", clock=lambda: 0.0)
    h = srv.submit([1, 2, 3, 4, 5], max_new_tokens=3)
    with faults.inject(faults.get_plan("fail_kth_call",
                                       op="page_migration", k=0)):
        srv.run()
    assert h.status == "done"
    spans = srv.obs.log.spans()
    migs = [s for s in spans if s.kind == "migration"]
    assert len(migs) >= 2, "one failed + one successful attempt"
    assert migs[0].attrs.get("error") == "InjectedFault"
    assert "error" not in migs[-1].attrs
    retries = [s for s in spans if s.kind == "retry"]
    assert retries and retries[0].attrs["op"] == "page_migration"
    # the policy's own backoff event rides the same log
    assert any(s.kind == "retry_backoff" for s in spans)
    # ...and the timeline interleaves: failed attempt -> retry ->
    # successful attempt.
    i_fail = spans.index(migs[0])
    i_ok = spans.index(migs[-1])
    i_retry = spans.index(retries[0])
    assert i_fail < i_retry < i_ok


def test_failover_events_in_timeline(role_engines):
    pf, dec = role_engines
    srv = DisaggServingEngine(dec, prefill_engine=pf, num_slots=2,
                              page=PAGE, prefill_buckets=(4, 16),
                              retry=RetryPolicy(max_attempts=2),
                              worker_fail_threshold=1,
                              telemetry="spans", clock=lambda: 0.0)
    srv.submit([9, 8, 7, 6, 5, 4], max_new_tokens=3)
    with faults.inject(faults.FaultPlan(
            name="hard", faults=(faults.Fault(
                "fail_call", op="page_migration", k=None),))):
        for _ in range(30):
            if srv._drained():
                break
            srv.step()
    srv.run()
    ks = _kinds(srv)
    assert "role_fail" in ks and "role_dead" in ks and "failover" in ks
    fo = next(s for s in srv.obs.log.spans() if s.kind == "failover")
    assert fo.attrs["requeued"] >= 1
    assert fo.attrs["target"] == "local"
    assert ks.index("role_dead") < ks.index("failover")
    assert srv.stats()["failovers"] == 1


def test_preempt_event_in_timeline(engine):
    srv = ServingEngine(engine, num_slots=2, page=PAGE, num_pages=3,
                        telemetry="spans", clock=lambda: 0.0)
    hs = [srv.submit(p, max_new_tokens=4)
          for p in ([1, 2, 3, 4, 5, 6], [7, 8, 9, 10, 11, 12])]
    srv.run()
    assert [h.status for h in hs] == ["done", "done"]
    pre = [s for s in srv.obs.log.spans() if s.kind == "preempt"]
    assert len(pre) == srv.stats()["preemptions"] >= 1
    assert pre[0].request_id is not None


def test_checkpoint_restore_spans(engine):
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        telemetry="spans", clock=lambda: 0.0)
    srv.submit([1, 2, 3], max_new_tokens=6)
    for _ in range(3):
        srv.step()
    snap = srv.checkpoint()
    assert "checkpoint" in _kinds(srv)
    srv2 = ServingEngine(engine, num_slots=2, page=PAGE,
                         telemetry="spans", clock=lambda: 0.0)
    srv2.restore(snap)
    ks = _kinds(srv2)
    assert "restore" in ks
    rs = next(s for s in srv2.obs.log.spans() if s.kind == "restore")
    assert rs.attrs["requests"] == 1
    srv.run()
    srv2.run()
    # A mid-stream revival records NO second TTFT (its first token
    # happened in the previous process) and no duplicate first_token
    # event — only the ITL chain restarts.
    lat = srv2.stats()["latency"]
    assert lat["ttft_ms"] is None
    assert "first_token" not in _kinds(srv2)
    assert lat["itl_ms"]["count"] >= 1


def test_chaos_events_carry_clock_stamps(role_engines):
    pf, dec = role_engines

    def factory():
        return DisaggServingEngine(
            dec, prefill_engine=pf, num_slots=2, page=PAGE,
            prefill_buckets=(4, 16), retry=RetryPolicy(max_attempts=2),
            worker_fail_threshold=2, telemetry="spans")

    rep = chaos.run_soak(factory, seed=5, ticks=25, n_faults=4)
    fired = [e for e in rep.events if e.fired]
    assert fired, "the soak must fire at least one fault"
    assert all(e.at is not None for e in fired), (
        "fired chaos events must carry engine-clock timestamps")
    assert all(e.at is None for e in rep.events if not e.fired)


# ---------------------------------------------------------------------------
# Bit-exactness + no-growth with spans active (the core contract)
# ---------------------------------------------------------------------------

def test_spans_bit_identical_and_jit_no_growth(engine):
    prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]
    runs = {}
    for mode in ("off", "spans"):
        srv = ServingEngine(engine, num_slots=2, page=PAGE,
                            prefill_buckets=(4, 8), telemetry=mode)
        runs[mode] = srv.generate(prompts, max_new_tokens=4)
        assert srv.decode_cache_size() == 1, (
            f"telemetry={mode} grew the decode jit cache")
        assert srv.prefill_cache_size() <= 2, (
            f"telemetry={mode} leaked a prefill shape")
    assert runs["off"] == runs["spans"], (
        "span recording changed token outputs")


def test_spec_spans_bit_identical(engine):
    prompts = [[1, 2, 3, 1, 2, 3, 1, 2], [5, 5, 5, 5]]
    runs = {}
    for mode in ("off", "spans"):
        srv = ServingEngine(engine, num_slots=2, page=PAGE, spec_k=3,
                            telemetry=mode)
        runs[mode] = srv.generate(prompts, max_new_tokens=6)
        assert srv.decode_cache_size() == 1
    assert runs["off"] == runs["spans"]


# ---------------------------------------------------------------------------
# Perfetto export well-formedness + the shared trace session
# ---------------------------------------------------------------------------

def test_merged_perfetto_export_well_formed(engine, tmp_path):
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        telemetry="spans")
    with srv.trace("obs-test", out_dir=str(tmp_path / "sess"),
                   xprof=False) as sess:
        srv.generate([[1, 2, 3], [4, 5]], max_new_tokens=3)
    path = sess.export()
    trace = json.load(open(path))          # json loads
    evs = trace["traceEvents"]
    host = [e for e in evs if e["pid"] == 1 and e.get("ph") in ("X", "i")]
    assert host, "host spans missing from the merged trace"
    # pid/tid stable: every host event on pid 1; slot-correlated spans
    # keep one tid per slot; numeric ts/dur everywhere.
    for e in host:
        assert e["pid"] == 1 and isinstance(e["tid"], int)
        assert isinstance(e["ts"], (int, float))
        if e["ph"] == "X":
            assert e["dur"] >= 0
    # one tid per slot, stable across the file
    tid_by_slot = {}
    for e in host:
        slot = e["args"].get("slot")
        if slot is not None:
            tid_by_slot.setdefault(slot, set()).add(e["tid"])
    assert tid_by_slot and all(len(tids) == 1
                               for tids in tid_by_slot.values())
    # spans nested: each request's queue_wait and decode-side work sits
    # inside its request span on the same clock.
    reqs = {e["args"]["request_id"]: e for e in host
            if e["args"]["kind"] == "request"}
    for e in host:
        rid = e["args"].get("request_id")
        if rid in reqs and e["ph"] == "X" and e is not reqs[rid]:
            r = reqs[rid]
            assert r["ts"] <= e["ts"] + 1e-6
            assert (e["ts"] + e.get("dur", 0)
                    <= r["ts"] + r["dur"] + 1e-6), (
                f"{e['args']['kind']} escapes its request span")
    # the xprof tier is honest about being skipped
    assert trace["metadata"]["xprof_reason"]
    # metrics snapshot rides the same session dir
    mp = sess.export_metrics(srv.stats())
    m = json.load(open(mp))
    assert m["stats"]["latency"]["ttft_ms"]["count"] == 2
    # old-signature compatibility: the session IS the directory path
    import os

    assert os.fspath(sess) == str(tmp_path / "sess")


def test_megakernel_slot_records_in_merged_trace(tmp_path):
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    cfg = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                           intermediate_size=32, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2,
                           head_dim=8)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    mk = MegaKernelEngine(cfg, mesh, batch=2, max_len=32, tile_w=16,
                          t_tile=16, num_cores=2, profile=True)
    srv = ServingEngine(mk, telemetry="spans")
    with srv.trace("mk-obs", out_dir=str(tmp_path / "mk"),
                   xprof=False, mk_keep=2) as sess:
        srv.generate([[1, 2, 3], [4, 5]], max_new_tokens=2)
    trace = json.load(open(sess.export()))
    evs = trace["traceEvents"]
    mk_evs = [e for e in evs if e["pid"] == 2 and "args" in e
              and "value" in e.get("args", {})]
    assert mk_evs, "megakernel slot records missing"
    steps = {e["args"]["step"] for e in mk_evs}
    assert len(steps) == 2, "mk_keep=2 retains two decode steps"
    names = {e["name"] for e in mk_evs}
    assert "LINEAR" in names or "RMSNORM" in names
    host = [e for e in evs if e["pid"] == 1]
    assert host, "host spans must ride the same file"


def test_trace_old_signature_still_works(engine):
    srv = ServingEngine(engine, num_slots=2, page=PAGE)
    # the pre-obs call shape: positional name, expert_histograms kw,
    # no interest in the yielded value.
    with srv.trace("compat-check", expert_histograms=False):
        srv.generate([[1, 2]], max_new_tokens=2)


# ---------------------------------------------------------------------------
# Resilience-layer units (event hooks)
# ---------------------------------------------------------------------------

def test_retry_policy_event_cb():
    events = []
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TimeoutError("transient")
        return "ok"

    pol = RetryPolicy(max_attempts=3)
    out, n = pol.call(flaky, op="x", retry_on=(TimeoutError,),
                      event_cb=lambda kind, **a: events.append(
                          (kind, a)),
                      sleep=lambda d: None)
    assert (out, n) == ("ok", 3)
    assert [k for k, _ in events] == ["retry_backoff", "retry_backoff"]
    assert events[0][1]["attempt"] == 1 and events[0][1]["op"] == "x"
    events.clear()
    calls.clear()
    with pytest.raises(TimeoutError):
        pol.call(lambda: (_ for _ in ()).throw(TimeoutError("t")),
                 op="y", retry_on=(TimeoutError,),
                 event_cb=lambda kind, **a: events.append((kind, a)),
                 sleep=lambda d: None)
    assert events[-1][0] == "retry_giveup"
    assert events[-1][1]["attempts"] == 3


def test_health_tracker_history_and_on_event():
    t = [100.0]
    events = []
    ht = HealthTracker(fail_threshold=2, clock=lambda: t[0],
                       on_event=lambda k, at, c: events.append(
                           (k, at, c)))
    ht.beat()                      # beats are not forwarded
    t[0] = 101.0
    ht.fail("first")
    t[0] = 102.0
    ht.fail("second")
    kinds = [k for k, _, _ in events]
    assert kinds == ["fail", "fail", "dead"]
    assert events[0][1] == 101.0 and events[1][1] == 102.0
    assert [h[1] for h in ht.history] == ["fail", "fail", "dead"]
    assert ht.history[0][0] == 101.0
