"""Generate the committed tiny Qwen3 checkpoint fixtures.

Run from the repo root:  python tests/fixtures/make_qwen3_tiny.py

Uses the REAL ``transformers`` Qwen3 model classes so the fixture's
key names, config.json semantics, and weight layouts are exactly what a
production checkpoint ships — the point of the fixture is catching
key-mapping drift in ``models/hf_loader.py`` against the actual HF
format (VERDICT r3 missing #4), not hand-rolled approximations.
"""

import os

import torch
from transformers import (Qwen3Config, Qwen3ForCausalLM,
                          Qwen3MoeConfig, Qwen3MoeForCausalLM)

HERE = os.path.dirname(os.path.abspath(__file__))


def make_dense():
    cfg = Qwen3Config(
        vocab_size=256, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=8,
        num_key_value_heads=4, head_dim=8, max_position_embeddings=128,
        rope_theta=1_000_000.0, tie_word_embeddings=False)
    torch.manual_seed(0)
    model = Qwen3ForCausalLM(cfg).float().eval()
    out = os.path.join(HERE, "qwen3_tiny")
    model.save_pretrained(out, safe_serialization=True)
    print("wrote", out)


def make_moe():
    cfg = Qwen3MoeConfig(
        vocab_size=256, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=8,
        num_key_value_heads=4, head_dim=8, max_position_embeddings=128,
        rope_theta=1_000_000.0, tie_word_embeddings=False,
        num_experts=8, num_experts_per_tok=2, moe_intermediate_size=32,
        norm_topk_prob=True, decoder_sparse_step=1,
        mlp_only_layers=[])
    torch.manual_seed(1)
    model = Qwen3MoeForCausalLM(cfg).float().eval()
    out = os.path.join(HERE, "qwen3_moe_tiny")
    model.save_pretrained(out, safe_serialization=True)
    print("wrote", out)


def make_next():
    """Tiny Qwen3-Next (hybrid GDN + gated attention + shared-expert
    MoE): 4 layers, 3 linear : 1 full, every head count divisible by
    the 8-device test mesh."""
    from transformers import Qwen3NextConfig, Qwen3NextForCausalLM

    cfg = Qwen3NextConfig(
        vocab_size=256, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=8,
        num_key_value_heads=8, head_dim=8,
        max_position_embeddings=128, rope_theta=1e4,
        partial_rotary_factor=0.25,
        linear_num_key_heads=8, linear_num_value_heads=16,
        linear_key_head_dim=4, linear_value_head_dim=4,
        linear_conv_kernel_dim=4,
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=16,
        shared_expert_intermediate_size=16, norm_topk_prob=True,
        decoder_sparse_step=1, mlp_only_layers=[],
        tie_word_embeddings=False)
    torch.manual_seed(2)
    model = Qwen3NextForCausalLM(cfg).float().eval()
    # Default-initialized RMSNorm weights are exactly zero
    # (zero-centered convention) and A_log/dt_bias are constants —
    # perturb everything so the parity test is numerically
    # load-bearing for every parameter.
    g = torch.Generator().manual_seed(3)
    with torch.no_grad():
        for p in model.parameters():
            p.add_(torch.randn(p.shape, generator=g) * 0.05)
    out = os.path.join(HERE, "qwen3_next_tiny")
    model.save_pretrained(out, safe_serialization=True)
    print("wrote", out)


if __name__ == "__main__":
    make_dense()
    make_moe()
    make_next()
