"""Pipeline-parallel schedule tests.

Reference pattern: ``benchmark/bench_pp.py`` + ``layers/nvidia/
pp_block.py`` — stage relay correctness and the microbatched schedule.
The key property (VERDICT r2 #4): each rank computes ONLY its own
stage, so per-rank FLOPs ≈ 1/S of the sequential total.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.layers.pp_comm import gpipe_forward, pipeline_forward
from triton_dist_tpu.utils.testing import spmd, assert_allclose

S = 8          # stages = ranks on the 8-device mesh
D = 64
M, MB = 16, 4  # microbatches x rows


def _stages_params(seed):
    w = jax.random.normal(jax.random.PRNGKey(seed), (S, D, D),
                          jnp.float32) * (D ** -0.5)
    return w


def _sequential(w, x_mb):
    h = x_mb.reshape(-1, D)
    for s in range(S):
        h = jnp.tanh(h @ w[s])
    return h.reshape(x_mb.shape)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_gpipe_equals_sequential(tp8_mesh, tp8_ctx, impl):
    w = _stages_params(0)
    x_mb = jax.random.normal(jax.random.PRNGKey(1), (M, MB, D))

    def run(w_loc, xs):
        return gpipe_forward(lambda h: jnp.tanh(h @ w_loc[0]), xs,
                             axis="tp", ctx=tp8_ctx, impl=impl)

    f = spmd(tp8_mesh, run, (P("tp", None, None), P(None, None, None)),
             P(None, None, None))
    assert_allclose(f(w, x_mb), _sequential(w, x_mb),
                    rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_gpipe_grad_equals_sequential(tp8_mesh, tp8_ctx, impl):
    """jax.grad through the scan+ppermute schedule IS the synchronous
    GPipe backward; gradients must match the sequential model. The
    pallas boundary differentiates through p2p_put's custom VJP
    (inverted-permutation transport)."""
    w = _stages_params(2)
    x_mb = jax.random.normal(jax.random.PRNGKey(3), (M, MB, D))

    def pp_loss(w_all, xs):
        # Inside shard_map the rank-local shard is w_all (1, D, D).
        out = gpipe_forward(lambda h: jnp.tanh(h @ w_all[0]), xs,
                            axis="tp", remat=True, impl=impl,
                            ctx=tp8_ctx if impl == "pallas" else None)
        # out is replicated but every rank's loss copy back-propagates
        # through the schedule's final psum (whose transpose sums
        # cotangents across ranks), so the per-rank loss must carry a
        # 1/n factor for the true global gradient.
        return jnp.sum(out ** 2) / jax.lax.axis_size("tp")

    g_pp = spmd(tp8_mesh,
                lambda w_, x_: jax.grad(pp_loss)(w_, x_),
                (P("tp", None, None), P(None, None, None)),
                P("tp", None, None))(w, x_mb)

    g_seq = jax.grad(lambda w_: jnp.sum(_sequential(w_, x_mb) ** 2))(w)
    assert_allclose(g_pp, g_seq, rtol=1e-4, atol=1e-4)


def test_gpipe_per_rank_flops(tp8_mesh, tp8_ctx):
    """Compiled per-device FLOPs of the schedule must be ~(M+S-1)/(M·S)
    of the sequential total — the whole point of replacing the masked
    relay (which burned S× on every rank)."""
    w = _stages_params(4)
    x_mb = jax.random.normal(jax.random.PRNGKey(5), (M, MB, D))

    def run(w_loc, xs):
        return gpipe_forward(lambda h: jnp.tanh(h @ w_loc[0]), xs,
                             axis="tp")

    f = jax.jit(jax.shard_map(
        run, mesh=tp8_mesh,
        in_specs=(P("tp", None, None), P(None, None, None)),
        out_specs=P(None, None, None), check_vma=False))
    cost = f.lower(w, x_mb).compile().cost_analysis()
    if isinstance(cost, (list, tuple)):   # older jax: one dict per device
        cost = cost[0] if cost else {}
    flops_pp = (cost or {}).get("flops", 0.0)
    if not flops_pp:
        # CPU/interpret backends report no flops in cost_analysis; the
        # jaxpr cost table counts the SAME per-device schedule (scan
        # trip counts x dot_general), so the assertion runs everywhere
        # instead of silently skipping off-silicon.
        from triton_dist_tpu.tools.perf_model import jaxpr_flops
        flops_pp = jaxpr_flops(jax.make_jaxpr(f)(w, x_mb))
    assert flops_pp > 0, "no flops from backend OR jaxpr walk"
    seq_flops = 2.0 * M * MB * D * D * S          # matmuls, whole model
    ticks = M + S - 1
    ideal = seq_flops * ticks / (M * S)
    # tanh/psum/where overhead allowed; the masked relay would be ~S×.
    assert flops_pp < 2.0 * ideal, (flops_pp, ideal, seq_flops)
    assert flops_pp < 0.5 * seq_flops


def test_gpipe_vs_relay(tp8_mesh, tp8_ctx):
    """The microbatched schedule and the unbatched relay agree on the
    same per-stage function."""
    x = jax.random.normal(jax.random.PRNGKey(6), (4, D))

    def relay(v):
        return pipeline_forward(lambda s, h: h + 1.0, v, num_stages=S,
                                axis="tp")

    def gpipe(v):
        return gpipe_forward(lambda h: h + 1.0, v[None], axis="tp")[0]

    r = spmd(tp8_mesh, relay, P(None, None), P(None, None))(x)
    g = spmd(tp8_mesh, gpipe, P(None, None), P(None, None))(x)
    assert_allclose(r, g, rtol=1e-6, atol=1e-6)
    assert_allclose(r, x + S, rtol=1e-6, atol=1e-6)
