"""Test bring-up: force an 8-device CPU mesh.

Must run before any JAX backend initializes. The axon TPU plugin (if
present) registers itself via sitecustomize and pins
``jax_platforms="axon,cpu"``; we flip back to CPU and force 8 host
devices so the whole distributed battery runs on one machine —
the single-host simulated-multi-rank harness the reference only has for
Ascend (``test/ascend/conftest.py:31-44`` run_dist_test).
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from triton_dist_tpu.parallel.mesh import MeshContext  # noqa: E402


NUM_DEVICES = 8


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running fault plans (subprocess deadlock harness); "
        "deselected from the tier-1 battery via -m 'not slow'")


@pytest.fixture(scope="session")
def tp8_mesh():
    """1D mesh: all 8 devices on the ``tp`` axis."""
    devices = jax.devices()
    assert len(devices) >= NUM_DEVICES, (
        f"need {NUM_DEVICES} devices, got {len(devices)} — conftest env "
        "setup ran too late?")
    return Mesh(np.array(devices[:NUM_DEVICES]), ("tp",))


@pytest.fixture(scope="session")
def tp8_ctx(tp8_mesh):
    return MeshContext.from_mesh(tp8_mesh)


@pytest.fixture(scope="session")
def dp2tp4_mesh():
    """2D mesh: 2 × 4 (dp × tp) — exercises logical-id linearization."""
    devices = jax.devices()[:NUM_DEVICES]
    return Mesh(np.array(devices).reshape(2, 4), ("dp", "tp"))


@pytest.fixture(scope="session")
def dp2tp4_ctx(dp2tp4_mesh):
    return MeshContext.from_mesh(dp2tp4_mesh)
