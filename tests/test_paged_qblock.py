"""Paged flash Q-BLOCK attention battery (kernel + serving wiring).

The contract under test: ``paged_flash_qblock`` — one Pallas kernel
for BOTH chunked prefill (C consecutive queries of one slot) and
speculative verification (K candidate queries per slot) — agrees with
the gather oracle on every pool dtype and edge shape, and switching
the serving engine to ``attn_impl="flash"`` changes TRAFFIC, never
tokens: greedy outputs stay exact vs ``Engine.serve`` across chunk
boundaries and speculative rollback, and no jit cache grows.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu.models import Engine, ModelConfig
from triton_dist_tpu.ops.chunked_prefill import gather_pages_dense
from triton_dist_tpu.ops.paged_flash_qblock import (
    paged_flash_qblock, paged_flash_qblock_ref,
)
from triton_dist_tpu.serving import ServingEngine
from triton_dist_tpu.serving.blocks import PagedKVCache

KVH = 2        # kv heads
REP = 2        # GQA ratio -> H = 4
HD = 8         # head dim
PAGE = 8       # tokens per page
P_MAX = 4      # pages per table row
H = KVH * REP
CAP = P_MAX * PAGE

TP = 4
CFG = ModelConfig.tiny()
MAX_LEN = 64
SRV_PAGE = 8


def _build(seed, b, num_pages=None):
    """Random pool + shuffled per-slot tables (page 0 = scratch)."""
    rng = np.random.RandomState(seed)
    num_pages = num_pages or (b * P_MAX + 1)
    kp = rng.randn(num_pages, KVH, PAGE, HD).astype(np.float32)
    vp = rng.randn(num_pages, KVH, PAGE, HD).astype(np.float32)
    perm = 1 + rng.permutation(num_pages - 1)[:b * P_MAX]
    tbl = perm.reshape(b, P_MAX).astype(np.int32)
    return kp, vp, tbl


def _quantize_pool(kp, vp, qdtype, qmax):
    """Whole-page max-abs quantization — the write_prompt blit's math."""
    ks = np.abs(kp).max(axis=(2, 3)) / qmax
    vs = np.abs(vp).max(axis=(2, 3)) / qmax
    ks = np.where(ks > 0, ks, 1.0).astype(np.float32)
    vs = np.where(vs > 0, vs, 1.0).astype(np.float32)
    kq = kp / ks[:, :, None, None]
    vq = vp / vs[:, :, None, None]
    if qdtype == jnp.int8:
        kq, vq = np.round(kq), np.round(vq)
    return (jnp.asarray(kq).astype(qdtype),
            jnp.asarray(vq).astype(qdtype),
            jnp.asarray(ks), jnp.asarray(vs))


def _run_both(q, kp, vp, tbl, pos, scales=()):
    kw = {}
    if scales:
        kw = dict(k_scale=scales[0], v_scale=scales[1])
    out = jax.jit(lambda *a: paged_flash_qblock(*a, **kw))(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tbl), jnp.asarray(pos))
    ref = paged_flash_qblock_ref(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tbl), jnp.asarray(pos), *scales)
    return np.asarray(out), np.asarray(ref)


# ---------------------------------------------------------------------------
# kernel == gather oracle
# ---------------------------------------------------------------------------

def test_qblock_matches_oracle_chunk_and_verify_shapes():
    """Both serving masks through one call: chunk-style consecutive
    positions (one slot mid-prompt) and verify-style lens+j positions,
    ragged across slots."""
    rng = np.random.RandomState(0)
    b, cq = 3, 5
    kp, vp, tbl = _build(1, b)
    q = rng.randn(b, cq, H, HD).astype(np.float32)
    pos = np.zeros((b, cq), np.int32)
    pos[0] = 9 + np.arange(cq)           # chunk at start=9
    pos[1] = 17 + np.arange(cq)          # verify at lens=17
    pos[2] = 2 + np.arange(cq)           # short history
    out, ref = _run_both(q, kp, vp, tbl, pos)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("qdtype,qmax", [
    (jnp.int8, 127.0),
    (jnp.float8_e4m3fn, 448.0),
])
def test_qblock_quantized_fused_dequant(qdtype, qmax):
    """int8/fp8 pools through the kernel's fused page-prefetch dequant
    == the dequantizing gather oracle, and both within quantization
    tolerance of the fp32 ground truth."""
    rng = np.random.RandomState(2)
    b, cq = 2, 4
    kp, vp, tbl = _build(3, b)
    kq, vq, ks, vs = _quantize_pool(kp, vp, qdtype, qmax)
    q = rng.randn(b, cq, H, HD).astype(np.float32)
    pos = np.stack([11 + np.arange(cq), 23 + np.arange(cq)]
                   ).astype(np.int32)
    out, ref = _run_both(q, kq, vq, tbl, pos, scales=(ks, vs))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
    exact, _ = _run_both(q, kp, vp, tbl, pos)
    tol = 5e-2 if qdtype == jnp.int8 else 2e-1
    assert np.abs(out - exact).max() < tol


def test_qblock_ragged_final_page():
    """Positions ending mid-page (neither page-aligned nor filling the
    final table entry) mask the page's tail exactly."""
    rng = np.random.RandomState(4)
    b, cq = 2, 3
    kp, vp, tbl = _build(5, b)
    q = rng.randn(b, cq, H, HD).astype(np.float32)
    pos = np.stack([PAGE + np.arange(cq),       # 1 page + partial
                    np.arange(cq)]).astype(np.int32)   # first page only
    out, ref = _run_both(q, kp, vp, tbl, pos)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_qblock_zero_len_parked_slot():
    """A parked slot (positions 0, scratch table row) stays finite and
    never perturbs live rows — the fixed-shape batch's empty lane."""
    rng = np.random.RandomState(6)
    b, cq = 2, 4
    kp, vp, tbl = _build(7, b)
    tbl[1] = 0                            # parked: all-scratch row
    q = rng.randn(b, cq, H, HD).astype(np.float32)
    pos = np.zeros((b, cq), np.int32)
    pos[0] = 13 + np.arange(cq)
    out, ref = _run_both(q, kp, vp, tbl, pos)
    assert np.isfinite(out).all(), "parked slot produced non-finite"
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
    # Live row unchanged when the parked slot's queries change.
    q2 = q.copy()
    q2[1] = rng.randn(cq, H, HD)
    out2, _ = _run_both(q2, kp, vp, tbl, pos)
    np.testing.assert_array_equal(out[0], out2[0])


def test_qblock_prefix_shared_pages():
    """Two slots whose tables share leading (prefix) pages: each
    attends the shared bytes plus its own private suffix — results
    match a pool where the prefix is duplicated."""
    rng = np.random.RandomState(8)
    b, cq = 2, 4
    kp, vp, tbl = _build(9, b)
    tbl[1, :2] = tbl[0, :2]               # share the first two pages
    q = rng.randn(b, cq, H, HD).astype(np.float32)
    pos = np.stack([2 * PAGE + 3 + np.arange(cq),
                    3 * PAGE + 1 + np.arange(cq)]).astype(np.int32)
    out, ref = _run_both(q, kp, vp, tbl, pos)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_qblock_position_beyond_capacity_raises():
    """A concrete position beyond one table row's capacity fails
    loudly, naming the slot (same contract as paged_flash_decode)."""
    rng = np.random.RandomState(10)
    kp, vp, tbl = _build(11, 1)
    q = rng.randn(1, 2, H, HD).astype(np.float32)
    pos = np.asarray([[CAP - 1, CAP]], np.int32)
    with pytest.raises(ValueError, match="slot 0.*capacity"):
        paged_flash_qblock(jnp.asarray(q), jnp.asarray(kp),
                           jnp.asarray(vp), jnp.asarray(tbl),
                           jnp.asarray(pos))


def test_qblock_scaleless_quantized_pool_raises():
    """A quantized pool without scales fails loudly in BOTH the kernel
    and the oracle instead of attending raw quantized bytes."""
    kp, vp, tbl = _build(12, 1)
    kq, vq, ks, vs = _quantize_pool(kp, vp, jnp.int8, 127.0)
    q = np.random.RandomState(13).randn(1, 2, H, HD).astype(np.float32)
    pos = np.asarray([[3, 4]], np.int32)
    with pytest.raises(ValueError, match="QUANTIZED pool"):
        paged_flash_qblock(jnp.asarray(q), kq, vq, jnp.asarray(tbl),
                           jnp.asarray(pos))
    with pytest.raises(ValueError, match="QUANTIZED pool"):
        paged_flash_qblock_ref(jnp.asarray(q), kq, vq,
                               jnp.asarray(tbl), jnp.asarray(pos))
    with pytest.raises(ValueError, match="unquantized"):
        paged_flash_qblock(jnp.asarray(q), jnp.asarray(kp),
                           jnp.asarray(vp), jnp.asarray(tbl),
                           jnp.asarray(pos), k_scale=ks, v_scale=vs)


def test_gather_pages_dense_one_definition():
    """The shared gather helper reproduces the PagedKVCache views it
    replaced — one definition for the oracle every paged kernel is
    tested against."""
    kp, vp, tbl = _build(14, 2)
    c = PagedKVCache(
        k_pages=jnp.asarray(kp)[None], v_pages=jnp.asarray(vp)[None],
        block_table=jnp.asarray(tbl),
        lens=jnp.asarray([5, 9], jnp.int32),
        live=jnp.ones((2,), jnp.int32))
    kd, vd = c.dense_layer(0)
    np.testing.assert_array_equal(
        np.asarray(kd),
        np.asarray(gather_pages_dense(jnp.asarray(kp),
                                      jnp.asarray(tbl))))
    kr, _ = c.dense_row(0, jnp.asarray(tbl[1]))
    np.testing.assert_array_equal(np.asarray(kr), np.asarray(kd)[1])


# ---------------------------------------------------------------------------
# serving wiring: flash changes traffic, never tokens
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine():
    mesh = Mesh(np.array(jax.devices()[:TP]), ("tp",))
    return Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=3)


def _baseline(engine, prompt, gen_len):
    ids = jnp.asarray(np.tile(np.asarray([prompt], np.int32), (TP, 1)))
    return np.asarray(engine.serve(ids, gen_len=gen_len))[0].tolist()


def test_chunk_boundary_token_exact_flash(engine):
    """Prompt lengths at b-1 / b / b+1 for bucket b through the FLASH
    chunk path: greedy tokens equal the monolithic Engine.serve run
    (chunk boundaries invisible to the math, kernel or gather)."""
    bucket = 8
    rng = np.random.RandomState(0)
    prompts = [[int(t) for t in rng.randint(0, CFG.vocab_size, n)]
               for n in (bucket - 1, bucket, bucket + 1)]
    want = [_baseline(engine, p, 8) for p in prompts]
    srv = ServingEngine(engine, num_slots=2, page=SRV_PAGE,
                        prefill_buckets=(4, bucket),
                        attn_impl="flash")
    got = srv.generate(prompts, max_new_tokens=8)
    assert got == want
    assert srv.stats()["chunk_attn"] == "flash"


def test_spec_rollback_token_exact_flash(engine):
    """Speculative decode through the FLASH verification kernel:
    rejected draft suffixes roll back page accounting and greedy
    outputs stay bit-identical to Engine.serve — acceptance is data,
    whichever kernel scored it."""
    prompts = [[1, 2, 3, 1, 2, 3], [4, 5], [6, 7, 8, 9], [5, 5, 5]]
    want = [_baseline(engine, p, 10) for p in prompts]
    srv = ServingEngine(engine, num_slots=2, page=SRV_PAGE, spec_k=4,
                        chunk_attn="flash")
    got = srv.generate(prompts, max_new_tokens=10)
    assert got == want
    st = srv.stats()
    # Mixed accept/reject actually exercised the rollback path.
    assert st["spec"]["drafted"] > st["spec"]["accepted"] > 0
    # Rollback left the pool clean: every page back on the free list.
    frag = st["pool"]
    assert frag["used_pages"] == 0, frag


def test_flash_matches_ref_tokens_quantized(engine):
    """attn_impl='flash' over an int8 pool produces the SAME tokens as
    the gather ref over the same int8 pool — the fused dequant and the
    gather dequant are the same math."""
    prompts = [[1, 2, 3, 4, 5, 6, 7], [9, 8, 7]]
    kw = dict(num_slots=2, page=SRV_PAGE, prefill_buckets=(4, 8),
              spec_k=3, kv_dtype="int8")
    got_f = ServingEngine(engine, attn_impl="flash", **kw).generate(
        prompts, max_new_tokens=8)
    got_r = ServingEngine(engine, attn_impl="ref", **kw).generate(
        prompts, max_new_tokens=8)
    assert got_f == got_r


def test_no_recompile_gates_with_flash(engine):
    """The serving no-growth gates hold with every flash path active:
    ONE decode(-side) jit entry after warmup and the chunk cache
    bounded by the bucket count — positions ride as data through the
    kernel exactly as through the gather."""
    rng = np.random.RandomState(1)
    srv = ServingEngine(engine, num_slots=2, page=SRV_PAGE,
                        prefill_buckets=(4, 8), spec_k=4,
                        attn_impl="flash")
    prompts = [[int(t) for t in rng.randint(0, CFG.vocab_size, n)]
               for n in (3, 5, 7, 9, 11, 13)]    # unseen lengths
    srv.generate(prompts, max_new_tokens=6)
    assert srv.decode_cache_size() == 1, srv.decode_cache_size()
    assert srv.prefill_cache_size() <= 2
    more = [[int(t) for t in rng.randint(0, CFG.vocab_size, n)]
            for n in (2, 6, 10)]
    srv.generate(more, max_new_tokens=4)
    assert srv.decode_cache_size() == 1
    assert srv.prefill_cache_size() <= 2


def test_bad_attn_impl_values_raise(engine):
    with pytest.raises(ValueError, match="attn_impl"):
        ServingEngine(engine, num_slots=2, page=SRV_PAGE,
                      attn_impl="pallas")
    with pytest.raises(ValueError, match="chunk_attn"):
        ServingEngine(engine, num_slots=2, page=SRV_PAGE,
                      chunk_attn="kernel")
