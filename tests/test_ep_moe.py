"""EP all-to-all / dispatch-combine / MoE tests.

Reference test pattern: ``test/nvidia/test_ep_a2a.py`` with the torch
dense-oracle in ``ep_a2a_utils.py``: dispatched+combined output must
equal running every token through its top-k experts directly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.layers import ep_moe, tp_moe
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.ops.all_to_all import all_to_all, all_to_all_ref
from triton_dist_tpu.ops.ep_a2a import (
    create_ep_context, ep_dispatch, ep_combine, ep_moe_ref,
)
from triton_dist_tpu.utils.testing import spmd, assert_allclose


def _rand(shape, seed, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype)


def test_all_to_all(tp8_mesh, tp8_ctx):
    # Per-shard (8, 4, 128): chunk r goes to rank r.
    x = _rand((64, 4, 128), 0)
    f = spmd(tp8_mesh, lambda v: all_to_all(v, ctx=tp8_ctx, axis="tp"),
             P("tp", None, None), P("tp", None, None))
    g = spmd(tp8_mesh, lambda v: all_to_all_ref(v, axis="tp"),
             P("tp", None, None), P("tp", None, None))
    assert_allclose(f(x), g(x))


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_ep_dispatch_combine_roundtrip(tp8_mesh, tp8_ctx, impl):
    """Identity experts: dispatch+combine must reproduce the weighted
    sum of the token itself."""
    T, d, E, K = 16, 32, 16, 2
    ctx = create_ep_context(tp8_ctx, num_experts=E, topk=K, capacity=2 * T,
                            axis="tp", impl=impl)
    tokens = _rand((8 * T, d), 1)
    ids = jax.random.randint(jax.random.PRNGKey(2), (8 * T, K), 0, E)
    w = jax.nn.softmax(_rand((8 * T, K), 3), axis=-1)

    def run(tok, ids_, w_):
        recv, rexp, state = ep_dispatch(tok, ids_, ctx)
        return ep_combine(recv, state, w_, ctx)

    f = spmd(tp8_mesh, run,
             (P("tp", None), P("tp", None), P("tp", None)),
             P("tp", None))
    out = f(tokens, ids, w)
    expected = tokens * jnp.sum(w, axis=-1, keepdims=True)
    assert_allclose(out, expected, rtol=1e-5, atol=1e-5)


def test_ep_moe_layer_vs_dense_oracle(tp8_mesh, tp8_ctx):
    cfg = ModelConfig.tiny_moe()
    T = 16  # per-rank tokens
    key = jax.random.PRNGKey(5)
    params = ep_moe.init(key, cfg)
    tokens = _rand((8 * T, cfg.hidden_size), 6)
    ctx = create_ep_context(tp8_ctx, num_experts=cfg.num_experts,
                            topk=cfg.num_experts_per_tok,
                            capacity=4 * T, axis="tp")

    # Distributed: params expert-sharded, tokens rank-sharded.
    f = spmd(tp8_mesh,
             lambda p, t: ep_moe.fwd(p, t, ctx,
                                     topk=cfg.num_experts_per_tok),
             (ep_moe.param_specs("tp"), P("tp", None)), P("tp", None))
    out = f(params, tokens)

    # Dense oracle on full weights.
    ids, w = ep_moe.route(params["router"], tokens,
                          cfg.num_experts_per_tok)

    def expert_fn(tok, e):
        g = tok @ params["w_gate"][e]
        u = tok @ params["w_up"][e]
        return ((jax.nn.silu(g.astype(jnp.float32)) * u.astype(jnp.float32))
                .astype(tok.dtype)) @ params["w_down"][e]

    expected = ep_moe_ref(tokens, ids, w, expert_fn, cfg.num_experts)
    assert_allclose(out, expected, rtol=1e-4, atol=1e-4)


def test_tp_moe_layer_vs_dense_oracle(tp8_mesh, tp8_ctx):
    cfg = ModelConfig.tiny_moe()
    params = ep_moe.init(jax.random.PRNGKey(7), cfg)
    tokens = _rand((64, cfg.hidden_size), 8)

    f = spmd(tp8_mesh,
             lambda p, t: tp_moe.fwd(p, t, topk=cfg.num_experts_per_tok,
                                     num_experts=cfg.num_experts,
                                     axis="tp"),
             (tp_moe.param_specs("tp"), P("tp", None)), P("tp", None))
    out = f(params, tokens)

    ids, w = ep_moe.route(params["router"], tokens,
                          cfg.num_experts_per_tok)

    def expert_fn(tok, e):
        g = tok @ params["w_gate"][e]
        u = tok @ params["w_up"][e]
        return ((jax.nn.silu(g.astype(jnp.float32)) * u.astype(jnp.float32))
                .astype(tok.dtype)) @ params["w_down"][e]

    expected = ep_moe_ref(tokens, ids, w, expert_fn, cfg.num_experts)
    assert_allclose(out, expected, rtol=1e-4, atol=1e-4)


def test_ep_dispatch_combine_dropfree_roundtrip(tp8_mesh, tp8_ctx):
    """Default (capacity=None) mode: exact-splits ragged dispatch.
    Identity experts roundtrip exactly, num_dropped is structurally 0."""
    T, d, E, K = 16, 32, 16, 2
    ctx = create_ep_context(tp8_ctx, num_experts=E, topk=K, axis="tp")
    tokens = _rand((8 * T, d), 30)
    ids = jax.random.randint(jax.random.PRNGKey(31), (8 * T, K), 0, E)
    w = jax.nn.softmax(_rand((8 * T, K), 32), axis=-1)

    def run(tok, ids_, w_):
        recv, rexp, state = ep_dispatch(tok, ids_, ctx)
        return ep_combine(recv, state, w_, ctx), state.num_dropped[None]

    f = spmd(tp8_mesh, run,
             (P("tp", None), P("tp", None), P("tp", None)),
             (P("tp", None), P("tp")))
    out, dropped = f(tokens, ids, w)
    expected = tokens * jnp.sum(w, axis=-1, keepdims=True)
    assert_allclose(out, expected, rtol=1e-5, atol=1e-5)
    assert int(np.sum(np.asarray(dropped))) == 0


def test_ep_dropfree_adversarial_skew_model_forward(tp8_mesh, tp8_ctx):
    """Worst-case routing skew — EVERY token on every rank routed to
    the experts of ONE rank — through the full MoE layer forward. The
    capped mode would drop most tokens here; the drop-free default must
    equal the dense oracle to float tolerance (VERDICT r2 #2)."""
    cfg = ModelConfig.tiny_moe()
    T = 16
    params = ep_moe.init(jax.random.PRNGKey(40), cfg)
    # Router forced: logits hugely favor experts 0 and 1 (both live on
    # rank 0 for tiny_moe's num_experts/8 layout).
    router = np.zeros((cfg.hidden_size, cfg.num_experts), np.float32)
    router[:, 0] = 40.0
    router[:, 1] = 20.0
    params["router"] = jnp.asarray(router)
    # Positive tokens: the linear router's logit is 40·sum(token), so a
    # negative-sum token would invert the intended skew.
    tokens = jnp.abs(_rand((8 * T, cfg.hidden_size), 41)) + 0.1
    ctx = create_ep_context(tp8_ctx, num_experts=cfg.num_experts,
                            topk=cfg.num_experts_per_tok, axis="tp")

    f = spmd(tp8_mesh,
             lambda p, t: ep_moe.fwd(p, t, ctx,
                                     topk=cfg.num_experts_per_tok),
             (ep_moe.param_specs("tp"), P("tp", None)), P("tp", None))
    out = f(params, tokens)

    ids, w = ep_moe.route(params["router"], tokens,
                          cfg.num_experts_per_tok)
    assert set(np.unique(np.asarray(ids))) <= {0, 1}  # skew took hold

    def expert_fn(tok, e):
        g = tok @ params["w_gate"][e]
        u = tok @ params["w_up"][e]
        return ((jax.nn.silu(g.astype(jnp.float32)) * u.astype(jnp.float32))
                .astype(tok.dtype)) @ params["w_down"][e]

    expected = ep_moe_ref(tokens, ids, w, expert_fn, cfg.num_experts)
    assert_allclose(out, expected, rtol=1e-4, atol=1e-4)


def test_ep_dropfree_quantized_wire(tp8_mesh, tp8_ctx):
    """Drop-free mode composes with on-wire quantization: scales ride a
    second ragged transport."""
    T, d, E, K = 16, 32, 16, 2
    ctx = create_ep_context(tp8_ctx, num_experts=E, topk=K, axis="tp",
                            wire_dtype=jnp.dtype("int8"))
    tokens = _rand((8 * T, d), 33)
    ids = jax.random.randint(jax.random.PRNGKey(34), (8 * T, K), 0, E)
    w = jax.nn.softmax(_rand((8 * T, K), 35), axis=-1)

    def run(tok, ids_, w_):
        recv, rexp, state = ep_dispatch(tok, ids_, ctx)
        return ep_combine(recv, state, w_, ctx)

    f = spmd(tp8_mesh, run,
             (P("tp", None), P("tp", None), P("tp", None)), P("tp", None))
    out = np.asarray(f(tokens, ids, w))
    expected = np.asarray(tokens * jnp.sum(w, axis=-1, keepdims=True))
    np.testing.assert_allclose(out, expected, rtol=0.08, atol=0.08)


def test_ep_capacity_overflow_drops(tp8_mesh, tp8_ctx):
    """Tokens beyond capacity are dropped (zero contribution), not
    corrupted."""
    T, d, E, K = 16, 32, 16, 2
    ctx = create_ep_context(tp8_ctx, num_experts=E, topk=K, capacity=1,
                            axis="tp")
    tokens = _rand((8 * T, d), 9)
    # All tokens to expert 0 → rank 0 capacity 1: only the first lands.
    ids = jnp.zeros((8 * T, K), jnp.int32)
    w = jnp.full((8 * T, K), 0.5)

    def run(tok, ids_, w_):
        recv, rexp, state = ep_dispatch(tok, ids_, ctx)
        return ep_combine(recv, state, w_, ctx)

    f = spmd(tp8_mesh, run,
             (P("tp", None), P("tp", None), P("tp", None)), P("tp", None))
    out = np.asarray(f(tokens, ids, w))
    tok_np = np.asarray(tokens)
    # First token of each rank-shard survives (k=0 slot 0); its k=1
    # copy overflows, so it contributes with weight 0.5 only.
    np.testing.assert_allclose(out[0], 0.5 * tok_np[0], rtol=1e-5)
    np.testing.assert_allclose(out[1], 0.0, atol=1e-6)


@pytest.mark.parametrize("wire", ["int8", "float8_e4m3fn"])
def test_ep_dispatch_combine_quantized_wire(tp8_mesh, tp8_ctx, wire):
    """On-wire quantization (reference ll-a2a-v2 fp8 mode): roundtrip
    within quantization tolerance."""
    T, d, E, K = 16, 32, 16, 2
    ctx = create_ep_context(tp8_ctx, num_experts=E, topk=K,
                            capacity=2 * T, axis="tp",
                            wire_dtype=jnp.dtype(wire))
    tokens = _rand((8 * T, d), 20)
    ids = jax.random.randint(jax.random.PRNGKey(21), (8 * T, K), 0, E)
    w = jax.nn.softmax(_rand((8 * T, K), 22), axis=-1)

    def run(tok, ids_, w_):
        recv, rexp, state = ep_dispatch(tok, ids_, ctx)
        return ep_combine(recv, state, w_, ctx)

    f = spmd(tp8_mesh, run,
             (P("tp", None), P("tp", None), P("tp", None)), P("tp", None))
    out = np.asarray(f(tokens, ids, w))
    expected = np.asarray(tokens * jnp.sum(w, axis=-1, keepdims=True))
    # Two quantization passes (dispatch + combine): ~1-2% error budget.
    np.testing.assert_allclose(out, expected, rtol=0.08, atol=0.08)


def test_ep_dispatch_2d_roundtrip(dp2tp4_mesh, dp2tp4_ctx):
    """Hierarchical (outer×inner) dispatch/combine: identity experts
    roundtrip exactly on a 2×4 mesh (dp axis standing in for DCN)."""
    from triton_dist_tpu.ops.ep_a2a import (
        create_ep2d_context, ep_dispatch_2d, ep_combine_2d,
    )
    T, d, E, K = 8, 32, 16, 2
    ctx = create_ep2d_context(dp2tp4_ctx, num_experts=E, topk=K,
                              outer_axis="dp", inner_axis="tp")
    tokens = _rand((8 * T, d), 70)
    ids = jax.random.randint(jax.random.PRNGKey(71), (8 * T, K), 0, E)
    w = jax.nn.softmax(_rand((8 * T, K), 72), axis=-1)

    def run(tok, ids_, w_):
        recv, rexp, state = ep_dispatch_2d(tok, ids_, ctx)
        return ep_combine_2d(recv, state, w_, ctx)

    f = spmd(dp2tp4_mesh, run,
             (P(("dp", "tp"), None), P(("dp", "tp"), None),
              P(("dp", "tp"), None)),
             P(("dp", "tp"), None))
    out = f(tokens, ids, w)
    expected = tokens * jnp.sum(w, axis=-1, keepdims=True)
    assert_allclose(out, expected, rtol=1e-5, atol=1e-5)


def test_ep_dispatch_2d_expert_placement(dp2tp4_mesh, dp2tp4_ctx):
    """Every assignment must land on the rank owning its expert, with
    the correct local expert id — checked by running a per-expert
    affine through the 2D route and comparing to the dense oracle,
    under adversarial skew (all tokens to one remote node's experts)."""
    from triton_dist_tpu.ops.ep_a2a import (
        create_ep2d_context, ep_dispatch_2d, ep_combine_2d,
    )
    T, d, E, K = 8, 32, 16, 2
    e_loc = E // 8
    ctx = create_ep2d_context(dp2tp4_ctx, num_experts=E, topk=K,
                              outer_axis="dp", inner_axis="tp")
    tokens = _rand((8 * T, d), 73)
    # Skew: everything routed to experts of global rank 7 (dcn 1, ici 3)
    ids = jnp.stack([jnp.full((8 * T,), 14, jnp.int32),
                     jnp.full((8 * T,), 15, jnp.int32)], axis=1)
    w = jax.nn.softmax(_rand((8 * T, K), 74), axis=-1)
    scale = jnp.arange(1, E + 1, dtype=jnp.float32)  # expert e: ×(e+1)

    def run(tok, ids_, w_):
        recv, rexp, state = ep_dispatch_2d(tok, ids_, ctx)
        # Per-rank expert compute: local expert l == global
        # rank·e_loc + l. Scale rows by their global expert id + 1.
        r_dp = jax.lax.axis_index("dp")
        r_tp = jax.lax.axis_index("tp")
        gexp = (r_dp * 4 + r_tp) * e_loc + rexp
        s = jnp.where(rexp >= 0, scale[jnp.clip(gexp, 0, E - 1)], 0.0)
        return ep_combine_2d(recv * s[:, None], state, w_, ctx)

    f = spmd(dp2tp4_mesh, run,
             (P(("dp", "tp"), None), P(("dp", "tp"), None),
              P(("dp", "tp"), None)),
             P(("dp", "tp"), None))
    out = f(tokens, ids, w)
    expected = ep_moe_ref(tokens, ids, w,
                          lambda tok, e: tok * scale[e], E)
    assert_allclose(out, expected, rtol=1e-5, atol=1e-5)


def test_moe_reduce_rs_vs_oracle(tp8_mesh, tp8_ctx):
    """Fused weighted-combine + ring reduce-scatter == XLA combine +
    psum_scatter (reference moe_reduce_rs pairing)."""
    from triton_dist_tpu.ops.moe_reduce import moe_reduce_rs, moe_reduce_rs_ref

    y = _rand((64, 2, 32), 50)   # (T, K, d), T = 8 ranks x 8
    w = jax.nn.softmax(_rand((64, 2), 51), axis=-1)

    f = spmd(tp8_mesh,
             lambda yy, ww: moe_reduce_rs(yy, ww, ctx=tp8_ctx, axis="tp",
                                          block_m=4, block_n=16),
             (P(None, None, None), P(None, None)), P("tp", None))
    g = spmd(tp8_mesh,
             lambda yy, ww: moe_reduce_rs_ref(yy, ww, axis="tp"),
             (P(None, None, None), P(None, None)), P("tp", None))
    assert_allclose(f(y, w), g(y, w), rtol=1e-5, atol=1e-5)


def test_moe_reduce_ar_vs_oracle(tp8_mesh, tp8_ctx):
    """Fused weighted-combine + one-shot allreduce == XLA combine +
    psum (reference moe_reduce_ar small-batch epilogue)."""
    from triton_dist_tpu.ops.moe_reduce import (
        moe_reduce_ar, moe_reduce_ar_ref,
    )

    y = _rand((8, 2, 64), 52)    # small T: the decode regime
    w = jax.nn.softmax(_rand((8, 2), 53), axis=-1)

    f = spmd(tp8_mesh,
             lambda yy, ww: moe_reduce_ar(yy, ww, ctx=tp8_ctx, axis="tp",
                                          block_n=16),
             (P(None, None, None), P(None, None)), P(None, None))
    g = spmd(tp8_mesh,
             lambda yy, ww: moe_reduce_ar_ref(yy, ww, axis="tp"),
             (P(None, None, None), P(None, None)), P(None, None))
    assert_allclose(f(y, w), g(y, w), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("epilogue", ["rs", "ar"])
def test_tp_moe_fully_fused_vs_layer(tp8_mesh, tp8_ctx, epilogue):
    """AG-fused grouped GEMM + Pallas down-proj + fused epilogue == the
    unfused layer path (reference allgather_group_gemm + moe_reduce_*
    pipeline)."""
    # 8 experts: the padded sorted layout is E·block_m-bounded, and the
    # ring workspace must sit well under the interpret harness's ~96 KB
    # starvation ceiling even when other pallas calls are in flight.
    cfg = ModelConfig.tiny_moe(num_experts=8)
    params = ep_moe.init(jax.random.PRNGKey(62), cfg)
    tokens = _rand((64, cfg.hidden_size), 63)

    fused = spmd(
        tp8_mesh,
        lambda p, t: tp_moe.fwd_fused(
            p, t, topk=cfg.num_experts_per_tok,
            num_experts=cfg.num_experts, mesh_ctx=tp8_ctx, axis="tp",
            # block_m=4 keeps the ring workspace under the ~96 KB ceiling
            # where the CPU interpret harness can deadlock (large
            # callback copies starve the 1-thread XLA CPU pool).
            block_m=4, epilogue=epilogue),
        (tp_moe.param_specs("tp"), P("tp", None)),
        P("tp", None) if epilogue == "rs" else P(None, None))(
            params, tokens)
    plain = spmd(
        tp8_mesh,
        lambda p, t: tp_moe.fwd(
            p, t, topk=cfg.num_experts_per_tok,
            num_experts=cfg.num_experts, axis="tp"),
        (tp_moe.param_specs("tp"), P("tp", None)),
        P("tp", None))(params, tokens)
    # "ar" returns the full (T, d) replicated; out_specs gather the
    # "rs" path to the same full shape at the host, so both compare
    # directly against the plain layer output.
    assert_allclose(fused, plain, rtol=2e-4, atol=2e-4)


def test_tp_moe_layer_fused_epilogue(tp8_mesh, tp8_ctx):
    """TP-MoE with the fused moe_reduce_rs epilogue == the psum_scatter
    layer path."""
    cfg = ModelConfig.tiny_moe()
    params = ep_moe.init(jax.random.PRNGKey(60), cfg)
    tokens = _rand((64, cfg.hidden_size), 61)

    def run(fused):
        return spmd(
            tp8_mesh,
            lambda p, t: tp_moe.fwd(
                p, t, topk=cfg.num_experts_per_tok,
                num_experts=cfg.num_experts, axis="tp",
                mesh_ctx=tp8_ctx if fused else None),
            (tp_moe.param_specs("tp"), P("tp", None)),
            P("tp", None))(params, tokens)

    assert_allclose(run(True), run(False), rtol=1e-4, atol=1e-4)


def test_ep_dropfree_recv_capacity_envelope(tp8_mesh, tp8_ctx):
    """Splits-sized drop-free mode: a static receive envelope far below
    n*T*K. Memory is proportional to the envelope (asserted on the
    receive buffer shape), and with the envelope >= the actual skewed
    receives the result is exactly the unclamped one."""
    T, d, E, K = 16, 32, 16, 2
    R = 3 * T * K  # 96 rows vs worst case n*T*K = 256
    ctx = create_ep_context(tp8_ctx, num_experts=E, topk=K, axis="tp",
                            recv_capacity=R)
    tokens = _rand((8 * T, d), 60)
    # Uniform routing: each rank receives ~T*K rows — well under R.
    ids = jax.random.randint(jax.random.PRNGKey(61), (8 * T, K), 0, E)
    w = jax.nn.softmax(_rand((8 * T, K), 62), axis=-1)

    def run(tok, ids_, w_):
        recv, rexp, state = ep_dispatch(tok, ids_, ctx)
        assert recv.shape[0] == R        # memory ∝ envelope
        return ep_combine(recv, state, w_, ctx), state.num_dropped[None]

    f = spmd(tp8_mesh, run,
             (P("tp", None), P("tp", None), P("tp", None)),
             (P("tp", None), P("tp")))
    out, dropped = f(tokens, ids, w)
    assert int(np.sum(np.asarray(dropped))) == 0
    expected = tokens * jnp.sum(w, axis=-1, keepdims=True)
    assert_allclose(out, expected, rtol=1e-5, atol=1e-5)


def test_ep_dropfree_recv_capacity_overflow_cut(tp8_mesh, tp8_ctx):
    """Adversarial skew overflowing the envelope: every assignment on
    every rank routes to rank 0's experts (8*T*K = 256 receives there),
    with an envelope of 80. The cut is deterministic (tail sources
    first), counted, and the combine still returns the exact weighted
    sum over the assignments that DID travel."""
    T, d, E, K = 16, 32, 16, 2
    R = 80
    ctx = create_ep_context(tp8_ctx, num_experts=E, topk=K, axis="tp",
                            recv_capacity=R)
    tokens = _rand((8 * T, d), 63)
    ids = jax.random.randint(jax.random.PRNGKey(64), (8 * T, K), 0, 2)
    w = jax.nn.softmax(_rand((8 * T, K), 65), axis=-1)

    def run(tok, ids_, w_):
        recv, rexp, state = ep_dispatch(tok, ids_, ctx)
        out = ep_combine(recv, state, w_, ctx)
        return out, state.num_dropped[None], state.valid

    f = spmd(tp8_mesh, run,
             (P("tp", None), P("tp", None), P("tp", None)),
             (P("tp", None), P("tp"), P("tp", None)))
    out, dropped, valid = f(tokens, ids, w)
    total_dropped = int(np.sum(np.asarray(dropped)))
    assert total_dropped == 8 * 8 * T * K // 8 - R * 1  # 256 - 80 = 176
    # Identity experts: surviving assignments contribute w * token.
    expected = tokens * jnp.sum(
        jnp.where(valid, w, 0.0), axis=-1, keepdims=True)
    assert_allclose(out, expected, rtol=1e-5, atol=1e-5)
