"""Real-format HF checkpoint parity (VERDICT r3 missing #4).

The committed fixtures under ``tests/fixtures/qwen3*_tiny/`` were
written by the REAL ``transformers`` Qwen3/Qwen3-MoE model classes
(``make_qwen3_tiny.py``), so their key names, config.json, and weight
layouts are exactly the production checkpoint format. Loading them
through ``hf_loader.load_hf_checkpoint`` and matching logits against
the torch reference forward catches BOTH key-mapping drift and math
drift (RoPE convention, per-head q/k norms, GQA, router semantics).
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.models import dense, qwen_moe
from triton_dist_tpu.models.hf_loader import load_hf_checkpoint
from triton_dist_tpu.parallel.mesh import MeshContext
from triton_dist_tpu.utils.testing import spmd

HERE = os.path.dirname(os.path.abspath(__file__))
DENSE_DIR = os.path.join(HERE, "fixtures", "qwen3_tiny")
MOE_DIR = os.path.join(HERE, "fixtures", "qwen3_moe_tiny")


def _torch_logits(path, ids):
    torch = pytest.importorskip("torch")
    from transformers import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(path).float().eval()
    with torch.no_grad():
        out = model(torch.from_numpy(np.asarray(ids))).logits
    return out.numpy()


@pytest.fixture(scope="module")
def mesh1():
    return Mesh(np.array(jax.devices()[:1]), ("tp",))


def test_dense_checkpoint_logits_parity(mesh1):
    cfg, params = load_hf_checkpoint(DENSE_DIR, dtype=jnp.float32)
    assert cfg.num_hidden_layers == 2 and cfg.head_dim == 8
    ids = np.array([[3, 17, 250, 9, 77, 1, 128, 64],
                    [5, 5, 200, 11, 0, 42, 7, 99]], np.int32)
    got = spmd(mesh1,
               lambda p, i: dense.forward_tokens(p, i, cfg, mode="xla"),
               (dense.param_specs(cfg), P(None, None)),
               P(None, None, None))(params, jnp.asarray(ids))
    want = _torch_logits(DENSE_DIR, ids)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3,
                               atol=2e-3)


def test_moe_checkpoint_logits_parity(mesh1):
    cfg, params = load_hf_checkpoint(MOE_DIR, dtype=jnp.float32)
    assert cfg.is_moe and cfg.num_experts == 8
    ids = np.array([[1, 30, 100, 200, 8, 16, 32, 64]], np.int32)
    got = spmd(mesh1,
               lambda p, i: qwen_moe.forward_tokens(
                   p, i, cfg, moe_impl="tp", mode="xla"),
               (qwen_moe.param_specs(cfg, moe_impl="tp"), P(None, None)),
               P(None, None, None))(params, jnp.asarray(ids))
    want = _torch_logits(MOE_DIR, ids)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3,
                               atol=2e-3)


def test_dense_checkpoint_sharded_matches_single(mesh1):
    """The same checkpoint served sharded on the 8-device mesh must
    reproduce the single-device logits (key mapping must commute with
    sharding)."""
    cfg, params = load_hf_checkpoint(DENSE_DIR, dtype=jnp.float32)
    ids = jnp.asarray(np.array([[9, 8, 7, 6, 5, 4, 3, 2]], np.int32))
    one = spmd(mesh1,
               lambda p, i: dense.forward_tokens(p, i, cfg, mode="xla"),
               (dense.param_specs(cfg), P(None, None)),
               P(None, None, None))(params, ids)
    # 4 kv heads over 8 ranks would need head replication; shard over 4.
    mesh4 = Mesh(np.array(jax.devices()[:4]), ("tp",))
    four = spmd(mesh4,
                lambda p, i: dense.forward_tokens(p, i, cfg, mode="xla"),
                (dense.param_specs(cfg), P(None, None)),
                P(None, None, None))(params, ids)
    np.testing.assert_allclose(np.asarray(four), np.asarray(one),
                               rtol=1e-4, atol=1e-4)
