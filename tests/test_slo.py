"""Multi-tenant SLO scheduling battery: per-tenant bounded queues and
quota backpressure, EDF ordering under a fake clock, DRR fairness,
aging (no starvation), noisy-neighbor isolation, priority preemption
through BOTH eviction paths (deterministic re-prefill and kv_tiers
park) token-exact vs ``Engine.serve``, class-aware timeout victims,
the router's (class, tenant over-quota) shed order, checkpoint/restore
with tenant queues, the chaos mini-soak with the tenant-fairness
invariants, and the decode jit-cache no-growth gate with SLO active
(docs/serving.md, "Multi-tenant SLO scheduling").

Everything is seeded and clock-injected — no wall-clock anywhere.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from triton_dist_tpu.models import Engine, ModelConfig
from triton_dist_tpu.resilience import chaos
from triton_dist_tpu.serving import (
    FleetRouter, QueueFullError, Request, Scheduler, ServingEngine,
    SLOScheduler, TenantSpec, deadline_class,
)

TP = 4
CFG = ModelConfig.tiny()
MAX_LEN = 64
PAGE = 8


@pytest.fixture(scope="module")
def engine():
    mesh = Mesh(np.array(jax.devices()[:TP]), ("tp",))
    return Engine(CFG, mesh, mode="xla", max_len=MAX_LEN, seed=3)


def _oracle(engine, prompt, gen_len):
    ids = jnp.asarray(np.tile(np.asarray([prompt], np.int32), (TP, 1)))
    return np.asarray(engine.serve(ids, gen_len=gen_len))[0].tolist()


# ---------------------------------------------------------------------------
# Pure host-side units (no device work): a stub engine exposes exactly
# the surface SLOScheduler touches.
# ---------------------------------------------------------------------------

class _StubObs:
    def event(self, *a, **k):
        pass


class _StubEngine:
    def __init__(self, num_slots=4, clock=None, **slo_kw):
        self.sched = Scheduler(num_slots, clock=clock or (lambda: 0.0))
        self.mega = False
        self.tiers = None
        self.manager = None
        self.obs = _StubObs()
        self.stats_counters = {"preemptions": 0, "slo_preemptions": 0}
        self._live = np.zeros(num_slots, np.int32)
        self._lens = np.zeros(num_slots, np.int32)
        self._toks = np.zeros(num_slots, np.int32)
        self.slo = SLOScheduler(**slo_kw)

    def submit(self, prompt, **kw):
        return self.slo.submit(self, Request(prompt=list(prompt), **kw))


def test_tenant_spec_validation():
    with pytest.raises(ValueError):
        TenantSpec("t", weight=0.0)
    with pytest.raises(ValueError):
        TenantSpec("t", max_queue=0)
    with pytest.raises(ValueError):
        TenantSpec("t", rate=-1.0)
    with pytest.raises(ValueError):
        TenantSpec("t", decode_quota=0.0)
    with pytest.raises(ValueError):
        TenantSpec("")
    with pytest.raises(ValueError):
        Scheduler(1).submit(Request(prompt=[1], slo_class="urgent"))


def test_deadline_class_derivation():
    assert deadline_class(Request(prompt=[1])) == "batch"
    assert deadline_class(Request(prompt=[1], deadline=9.0)) \
        == "interactive"
    assert deadline_class(Request(prompt=[1], slo_class="standard")) \
        == "standard"
    # Explicit class wins over the deadline-derived one.
    assert deadline_class(Request(prompt=[1], deadline=9.0,
                                  slo_class="batch")) == "batch"


def test_edf_ordering_fake_clock():
    """Within one tenant and class, releases are earliest-deadline
    first regardless of submission order (FIFO breaks the tie)."""
    clock = [0.0]
    eng = _StubEngine(num_slots=3, clock=lambda: clock[0])
    a = eng.submit([1], deadline=50.0)
    b = eng.submit([2], deadline=20.0)
    c = eng.submit([3], deadline=80.0)
    eng.slo.pump(eng)
    assert list(eng.sched.queue) == [b, a, c]


def test_drr_fairness_sweep():
    """Weight-proportional fair share: weight 3 vs weight 1 releases
    3:1 over any window, deterministically."""
    eng = _StubEngine(
        num_slots=1,
        specs=[{"name": "a", "weight": 1.0, "max_queue": 64},
               {"name": "b", "weight": 3.0, "max_queue": 64}])
    for i in range(40):
        eng.submit([i + 1], tenant="a")
        eng.submit([i + 1], tenant="b")
    order = [eng.slo._next(0.0).request.tenant for _ in range(20)]
    assert order.count("a") == 5 and order.count("b") == 15
    # Re-running the same trace releases in the same order.
    eng2 = _StubEngine(
        num_slots=1,
        specs=[{"name": "a", "weight": 1.0, "max_queue": 64},
               {"name": "b", "weight": 3.0, "max_queue": 64}])
    for i in range(40):
        eng2.submit([i + 1], tenant="a")
        eng2.submit([i + 1], tenant="b")
    order2 = [eng2.slo._next(0.0).request.tenant for _ in range(20)]
    assert order == order2


def test_aging_promotes_batch_no_starvation():
    """A queued batch request's effective class rank rises with wait
    (age_boost_s), so a steady interactive stream cannot starve it."""
    clock = [0.0]
    eng = _StubEngine(num_slots=1, clock=lambda: clock[0],
                      age_boost_s=1.0)
    old = eng.submit([1], tenant="bulk")            # batch, rank 2
    clock[0] = 2.5                                  # aged to rank 0
    fresh = eng.submit([2], tenant="chat", deadline=100.0)
    first = eng.slo._next(clock[0])
    second = eng.slo._next(clock[0])
    assert first is old, "aged batch request did not reach the front"
    assert second is fresh


def test_rate_bucket_and_bounded_queue_backpressure():
    """Per-tenant admission control: the flooding tenant's own
    QueueFullError, while another tenant keeps admitting."""
    clock = [0.0]
    eng = _StubEngine(
        num_slots=1, clock=lambda: clock[0],
        specs=[{"name": "noisy", "max_queue": 3, "rate": 1.0,
                "burst": 2}])
    eng.submit([1], tenant="noisy")
    eng.submit([2], tenant="noisy")
    with pytest.raises(QueueFullError, match="noisy.*rate-limited"):
        eng.submit([3], tenant="noisy")       # burst of 2 exhausted
    eng.submit([4], tenant="calm")            # other tenant admits
    clock[0] = 1.0                            # 1s refills one token
    eng.submit([5], tenant="noisy")
    # Now the bounded queue is the limit (3 queued).
    clock[0] = 10.0
    with pytest.raises(QueueFullError, match="noisy.*queue full"):
        eng.submit([6], tenant="noisy")
    assert eng.slo.stats()["tenants"]["noisy"]["rejected"] == 2


def test_decode_quota_gates_release():
    """A tenant with an exhausted decode-token bucket stays queued
    (never failed) until refill; other tenants release past it."""
    clock = [0.0]
    eng = _StubEngine(
        num_slots=2, clock=lambda: clock[0],
        specs=[{"name": "metered", "decode_quota": 2.0}])
    m = eng.submit([1], tenant="metered")
    other = eng.submit([2], tenant="free")
    st = eng.slo.registry.state("metered")
    st.tokens = 0.0                           # bucket spent
    st.charged += st.granted                  # keep the algebra exact
    eng.slo.pump(eng)
    assert list(eng.sched.queue) == [other]   # metered held back
    assert m.status == "queued"
    clock[0] = 1.0                            # refill 2 tokens
    eng.slo.pump(eng)
    assert m in eng.sched.queue


# ---------------------------------------------------------------------------
# Class-aware timeout victims (scheduler regression)
# ---------------------------------------------------------------------------

def test_timeout_victims_class_aware():
    """A wedged dispatch fails batch-class victims before interactive
    ones — eldest within the class, slot id as the final tiebreak."""
    clock = [10.0]
    s = Scheduler(3, clock=lambda: clock[0])
    inter = s.submit(Request(prompt=[1], deadline=1e9))
    old_batch = s.submit(Request(prompt=[2]))
    new_batch = s.submit(Request(prompt=[3]))
    clock[0] = 11.0
    s.admit()                                  # all placed together
    # Stagger ages: old_batch started earlier than new_batch.
    old_batch.started_at = 11.0
    new_batch.started_at = 12.0
    inter.started_at = 5.0                     # eldest overall
    v = s.timeout_victims()
    assert v == [old_batch], (
        "victim must be the eldest BATCH request, not the eldest "
        "overall")
    # Same class everywhere -> eldest wins (the pre-SLO behaviour).
    s2 = Scheduler(2, clock=lambda: clock[0])
    a = s2.submit(Request(prompt=[1]))
    b = s2.submit(Request(prompt=[2]))
    s2.admit()
    a.started_at, b.started_at = 3.0, 2.0
    assert s2.timeout_victims() == [b]


# ---------------------------------------------------------------------------
# Serving-path behaviour (real engine)
# ---------------------------------------------------------------------------

def _serve_mixed(engine, *, slo, n_bulk=5, bulk_gen=8, n_chat=3,
                 chat_gen=4, **srv_kw):
    """Seeded mixed-tenant trace: a bulk batch flood up front, then
    interactive chat arrivals every 2 ticks. The fake clock advances
    1.0 per tick, so TTFT is measured in ticks."""
    clock = [0.0]
    srv = ServingEngine(engine, num_slots=2, page=PAGE,
                        clock=lambda: clock[0], slo=slo, **srv_kw)
    bulk = [srv.submit([i + 1, 2, 3], max_new_tokens=bulk_gen,
                       tenant="bulk") for i in range(n_bulk)]
    chat = []
    tick = 0
    while not srv._drained() or len(chat) < n_chat:
        if tick % 2 == 0 and len(chat) < n_chat:
            chat.append(srv.submit([40 + len(chat), 7],
                                   max_new_tokens=chat_gen,
                                   tenant="chat", deadline=1e9))
        srv.step()
        clock[0] += 1.0
        tick += 1
        assert tick < 500, "mixed trace failed to drain"
    return srv, bulk, chat


def test_noisy_neighbor_isolation(engine):
    """The batch flood must not move interactive TTFT: with SLO armed,
    chat p99 TTFT stays within a small tick bound AND beats the FIFO
    baseline; every stream stays bit-identical to the single-tenant
    oracle."""
    def p99(srv):
        lat = srv.stats()["latency"]
        return lat["per_tenant"]["chat"]["ttft_ms"]["p99"]

    fifo, fb, fc = _serve_mixed(engine, slo=None)
    tuned, tb, tc = _serve_mixed(
        engine, slo={"preempt_margin_s": 1e12})
    for h in fb + tb:
        assert h.tokens == _oracle(engine, list(h.request.prompt), 8)
    for h in fc + tc:
        assert h.tokens == _oracle(engine, list(h.request.prompt), 4)
    assert p99(tuned) < p99(fifo), (
        "SLO scheduling did not improve interactive p99 TTFT "
        f"({p99(tuned)} vs FIFO {p99(fifo)})")
    # Absolute bound: a chat request waits at most a few ticks (one
    # preemption + admission), never behind the whole bulk backlog.
    assert p99(tuned) <= 6 * 1e3          # 6 ticks in ms
    st = tuned.stats()
    assert st["slo_preemptions"] >= 1
    assert st["slo_attainment"] == 1.0


def test_preempt_reprefill_token_exact(engine):
    """The re-prefill eviction path: a preempted bulk request re-enters
    through its TENANT queue and finishes bit-identical to the
    oracle; the decode jit cache never grows."""
    srv, bulk, chat = _serve_mixed(engine,
                                   slo={"preempt_margin_s": 1e12})
    st = srv.stats()
    assert st["slo_preemptions"] >= 1
    assert st["parks"] == 0               # no tier store -> re-prefill
    assert st["slo"]["tenants"]["bulk"]["preempted"] >= 1
    assert all(h.status == "done" for h in bulk + chat)
    assert srv.decode_cache_size() == 1


def test_preempt_park_token_exact(engine):
    """The park eviction path (kv_tiers armed): the victim's KV
    offloads to the tier, auto-resumes when pressure subsides, and
    the stream stays bit-identical."""
    srv, bulk, chat = _serve_mixed(
        engine, slo={"preempt_margin_s": 1e12},
        kv_tiers=True, prefix_reuse=True)
    st = srv.stats()
    assert st["slo_preemptions"] >= 1
    assert st["parks"] >= 1 and st["resumes"] >= 1
    assert all(h.status == "done" for h in bulk + chat)
    assert not srv.slo._parked_by_slo    # preemption debt fully paid
    assert srv.decode_cache_size() == 1


def test_decode_cache_no_growth_with_slo(engine):
    """The fixed-decode-shape gate with SLO + quotas + preemption
    active: one jit entry after the full mixed-tenant trace."""
    srv, _, _ = _serve_mixed(
        engine,
        slo={"specs": [{"name": "bulk", "decode_quota": 50.0},
                       {"name": "chat", "weight": 2.0}],
             "preempt_margin_s": 1e12})
    assert srv.decode_cache_size() == 1
    assert srv.prefill_cache_size() is None or \
        srv.prefill_cache_size() >= 1


def test_checkpoint_restore_with_tenant_queues(engine):
    """Tenant-queued handles snapshot as QUEUED and re-adopt into the
    restoring engine's SLO layer; streams stay token-exact."""
    def build():
        return ServingEngine(engine, num_slots=1, page=PAGE,
                             clock=lambda: 0.0, slo=True)

    srv = build()
    hs = [srv.submit([i + 1, 5], max_new_tokens=4, tenant=f"t{i % 2}",
                     request_id=f"ck-{i}") for i in range(3)]
    srv.step()                           # first one reaches a slot
    snap = srv.checkpoint()
    assert sum(1 for h in snap["handles"]
               if h["status"] == "queued") >= 2
    srv2 = build()
    revived = {h.request.request_id: h for h in srv2.restore(snap)}
    assert len(revived) == 3
    assert srv2.slo.queued_handles()      # re-adopted, not sched-queued
    srv2.run()
    for i in range(3):
        got = revived[f"ck-{i}"].tokens
        assert got == _oracle(engine, [i + 1, 5], 4)


# ---------------------------------------------------------------------------
# Router: tenant-aware shed order
# ---------------------------------------------------------------------------

def _factory(engine, **kw):
    def make():
        kw.setdefault("num_slots", 1)
        kw.setdefault("page", PAGE)
        kw.setdefault("prefix_reuse", True)
        kw.setdefault("kv_tiers", True)
        return ServingEngine(engine, **kw)
    return make


def test_router_shed_order_class_and_tenant(engine):
    """Saturated overflow: an interactive arrival displaces a QUEUED
    batch request (shed order = class first, over-quota tenant first)
    instead of being dropped, and ``shed_by_tenant`` attributes the
    shed to the flooding tenant."""
    router = FleetRouter(_factory(engine, max_queue=1), fleets=2,
                         max_queue=1)
    # Saturate both fleet queues + the router queue with one tenant's
    # batch flood.
    flood = [router.submit([i + 1, 2], max_new_tokens=2,
                           tenant="flood") for i in range(3)]
    assert len(router.queue) == 1
    inter = router.submit(Request(prompt=[9, 9], max_new_tokens=2,
                                  deadline=1e9, tenant="victim"))
    shed = [h for h in flood if h.status == "shed"]
    assert len(shed) == 1, "queued batch request was not displaced"
    assert inter.status == "queued" or inter.slot is not None
    st = router.stats()
    assert st["shed_requests"] == 1
    assert st["shed_by_tenant"] == {"flood": 1}
    router.run()
    assert inter.status == "done"
    assert inter.tokens == _oracle(engine, [9, 9], 2)


def test_router_slo_aggregation(engine):
    """Per-fleet SLO quota views aggregate in ``router.stats()``
    (nulled, never omitted, when the fleets run without SLO)."""
    router = FleetRouter(_factory(engine), fleets=2, max_queue=4)
    assert router.stats()["slo"] is None
    assert router.stats()["slo_attainment"] is None
    router2 = FleetRouter(_factory(engine, slo=True), fleets=2,
                          max_queue=4)
    hs = [router2.submit([i + 1, 3], max_new_tokens=2,
                         tenant=f"t{i % 2}",
                         deadline=1e9) for i in range(4)]
    router2.run()
    st = router2.stats()
    assert all(h.status == "done" for h in hs)
    assert st["slo"] is not None
    assert st["slo_attainment"] == 1.0
    assert set(st["slo"]["tenants"]) == {"t0", "t1"}
    admitted = sum(t["admitted"] for t in st["slo"]["tenants"].values())
    assert admitted == 4


# ---------------------------------------------------------------------------
# Chaos: the tenant-fairness invariants
# ---------------------------------------------------------------------------

def test_slo_invariant_checker_teeth(engine):
    """The new sweep actually bites: smashed quota algebra and dual
    ownership raise InvariantViolation."""
    srv = ServingEngine(engine, num_slots=1, page=PAGE,
                        clock=lambda: 0.0,
                        slo={"specs": [{"name": "m",
                                        "decode_quota": 4.0}]})
    h = srv.submit([1, 2], max_new_tokens=2, tenant="m")
    chaos.check_invariants(srv)
    st = srv.slo.registry.state("m")
    st.charged += 3                      # quota leak
    with pytest.raises(chaos.InvariantViolation, match="conserved"):
        chaos.check_invariants(srv)
    st.charged -= 3
    chaos.check_invariants(srv)
    srv.sched.queue.append(h)            # dual ownership
    with pytest.raises(chaos.InvariantViolation, match="dual"):
        chaos.check_invariants(srv)
    srv.sched.queue.clear()
    h.queued_at = -1e6                   # starved beyond the bound
    with pytest.raises(chaos.InvariantViolation, match="starved"):
        chaos.check_invariants(srv)


def test_slo_mini_soak(engine):
    """Seeded multi-tenant chaos soak with the SLO layer armed: the
    tenant-fairness invariants hold every tick and every survivor is
    token-exact vs the fault-free oracle."""
    def factory():
        return ServingEngine(
            engine, num_slots=2, page=PAGE, prefix_reuse=True,
            kv_tiers=True,
            slo={"specs": [{"name": "a", "weight": 2.0,
                            "max_queue": 32},
                           {"name": "b", "max_queue": 32},
                           {"name": "c", "rate": 50.0, "burst": 16,
                            "max_queue": 32}],
                 "preempt_margin_s": 0.0})

    rep = chaos.run_soak(factory, seed=7, ticks=40, n_faults=4,
                         tenants=("a", "b", "c"))
    assert rep.survived_faults == rep.faults_injected == 4
    assert rep.invariant_checks >= rep.ticks
    assert rep.token_exact_requests == rep.requests["done"] > 0
    assert rep.requests["submitted"] == sum(
        rep.requests[k] for k in ("done", "failed", "timeout"))
