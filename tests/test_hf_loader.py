"""HF state-dict mapping (reference: models/dense.py:150 loads HF
checkpoints). Uses a synthetic torch state dict; weights must land
transposed into the (in, out) layout and produce identical logits to
directly-constructed params."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.hf_loader import params_from_hf_state_dict
from triton_dist_tpu.models import dense


def _fake_state_dict(cfg, rng):
    d, ff, hd = cfg.hidden_size, cfg.intermediate_size, cfg.head_dim
    h, kvh = cfg.num_attention_heads, cfg.num_key_value_heads
    sd = {}
    def w(*shape):
        return rng.standard_normal(shape).astype(np.float32) * 0.05
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        sd[p + "self_attn.q_proj.weight"] = w(h * hd, d)
        sd[p + "self_attn.k_proj.weight"] = w(kvh * hd, d)
        sd[p + "self_attn.v_proj.weight"] = w(kvh * hd, d)
        sd[p + "self_attn.o_proj.weight"] = w(d, h * hd)
        sd[p + "self_attn.q_norm.weight"] = w(hd)
        sd[p + "self_attn.k_norm.weight"] = w(hd)
        sd[p + "mlp.gate_proj.weight"] = w(ff, d)
        sd[p + "mlp.up_proj.weight"] = w(ff, d)
        sd[p + "mlp.down_proj.weight"] = w(d, ff)
        sd[p + "input_layernorm.weight"] = w(d)
        sd[p + "post_attention_layernorm.weight"] = w(d)
    sd["model.embed_tokens.weight"] = w(cfg.vocab_size, d)
    sd["model.norm.weight"] = w(d)
    sd["lm_head.weight"] = w(cfg.vocab_size, d)
    return sd


def test_hf_mapping_shapes_and_layout():
    cfg = ModelConfig.tiny()
    sd = _fake_state_dict(cfg, np.random.RandomState(0))
    params = params_from_hf_state_dict(sd, cfg, dtype=jnp.float32)
    ref = dense.init_params(jax.random.PRNGKey(0), cfg)
    # Same tree structure and shapes as directly-initialized params.
    jax.tree.map(lambda a, b: (_ for _ in ()).throw(
        AssertionError(f"{a.shape} != {b.shape}"))
        if a.shape != b.shape else None, params, ref)
    # Torch stores (out, in); ours is (in, out): check one transpose.
    np.testing.assert_allclose(
        np.asarray(params["layers"][0]["attn"]["wq"]),
        sd["model.layers.0.self_attn.q_proj.weight"].T)


def test_hf_tied_embeddings():
    import dataclasses
    cfg = dataclasses.replace(ModelConfig.tiny(),
                              tie_word_embeddings=True)
    sd = _fake_state_dict(cfg, np.random.RandomState(1))
    del sd["lm_head.weight"]
    params = params_from_hf_state_dict(sd, cfg, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(params["lm_head"]),
                               np.asarray(params["embed"]))


def test_checkpoint_roundtrip(tmp_path):
    """Params round-trip through the orbax checkpointer with shardings
    restored device-direct (models/checkpoint.py)."""
    from triton_dist_tpu.models import checkpoint

    cfg = ModelConfig.tiny()
    params = dense.init_params(jax.random.PRNGKey(5), cfg)
    path = checkpoint.save_params(str(tmp_path / "ckpt"), params)
    back = checkpoint.restore_params(path, like=params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b)), params, back)


def test_from_hf_config_requires_core_fields():
    """Core architecture fields must raise when absent — a malformed
    config.json must not silently build a default-shaped model
    (ADVICE r4)."""
    import pytest
    from triton_dist_tpu.models.config import ModelConfig

    good = {"vocab_size": 128, "hidden_size": 32,
            "num_hidden_layers": 2, "num_attention_heads": 4}
    assert ModelConfig.from_hf_config(good).hidden_size == 32
    for missing in good:
        bad = {k: v for k, v in good.items() if k != missing}
        with pytest.raises(KeyError):
            ModelConfig.from_hf_config(bad)


def test_from_hf_config_gdn_key_heads_split():
    cfg = {"vocab_size": 128, "hidden_size": 32,
           "num_hidden_layers": 2, "num_attention_heads": 4,
           "linear_num_value_heads": 8, "linear_num_key_heads": 4}
    mc = ModelConfig.from_hf_config(cfg)
    assert mc.gdn_num_heads == 8 and mc.gdn_num_key_heads == 4


def test_moe_mapper_bias_checkpoint_matches_init_tree():
    """The MoE mapper shares _attn_from_hf with the dense mapper: a
    bias-carrying, norm-free (qwen2_moe-style) MoE state dict must land
    on exactly the tree `qwen_moe.init_params` builds for that config."""
    import dataclasses
    from triton_dist_tpu.models.hf_loader import (
        moe_params_from_hf_state_dict)
    from triton_dist_tpu.models import qwen_moe

    cfg = dataclasses.replace(ModelConfig.tiny_moe(),
                              attention_bias=True, qk_norm=False)
    rng = np.random.RandomState(2)
    d, ff, hd = cfg.hidden_size, cfg.moe_intermediate_size, cfg.head_dim
    h, kvh = cfg.num_attention_heads, cfg.num_key_value_heads
    def w(*shape):
        return rng.standard_normal(shape).astype(np.float32) * 0.05
    sd = {}
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        sd[p + "self_attn.q_proj.weight"] = w(h * hd, d)
        sd[p + "self_attn.k_proj.weight"] = w(kvh * hd, d)
        sd[p + "self_attn.v_proj.weight"] = w(kvh * hd, d)
        sd[p + "self_attn.o_proj.weight"] = w(d, h * hd)
        sd[p + "self_attn.q_proj.bias"] = w(h * hd)
        sd[p + "self_attn.k_proj.bias"] = w(kvh * hd)
        sd[p + "self_attn.v_proj.bias"] = w(kvh * hd)
        sd[p + "mlp.gate.weight"] = w(cfg.num_experts, d)
        for e in range(cfg.num_experts):
            q = f"{p}mlp.experts.{e}."
            sd[q + "gate_proj.weight"] = w(ff, d)
            sd[q + "up_proj.weight"] = w(ff, d)
            sd[q + "down_proj.weight"] = w(d, ff)
        sd[p + "input_layernorm.weight"] = w(d)
        sd[p + "post_attention_layernorm.weight"] = w(d)
    sd["model.embed_tokens.weight"] = w(cfg.vocab_size, d)
    sd["model.norm.weight"] = w(d)
    sd["lm_head.weight"] = w(cfg.vocab_size, d)

    params = moe_params_from_hf_state_dict(sd, cfg, dtype=jnp.float32)
    ref = qwen_moe.init_params(jax.random.PRNGKey(0), cfg)
    jax.tree.map(lambda a, b: (_ for _ in ()).throw(
        AssertionError(f"{a.shape} != {b.shape}"))
        if a.shape != b.shape else None, params, ref)
    attn = params["layers"][0]["attn"]
    assert "q_norm" not in attn
    np.testing.assert_allclose(
        np.asarray(attn["bq"]),
        sd["model.layers.0.self_attn.q_proj.bias"])
    # o_proj.bias absent in qwen2_moe checkpoints -> zeros.
    assert np.all(np.asarray(attn["bo"]) == 0.0)
