"""Hierarchical 2-hop EP decode dispatch battery (ISSUE 18 / ROADMAP
open item 1: kill the ``ll``→``ar`` fallback on multi-node meshes).

Covers the ``ll2d`` transport end to end: ``ll_a2a_2d`` hop semantics
vs the flat wire reference (int8 + fp8, kernel + xla hop impls),
``fwd_decode`` parity with the ``"ar"`` oracle under uniform and
adversarially skewed routing, serving-level greedy-token exactness
with the ``dispatch_transport`` observability line, the DCN
put-coalescing claim ASSERTED from the trace-time put ledger (puts per
dispatch == peer-NODE count, not peer-chip count), per-hop fault
containment, the 2D-keyed tune round-trip, and the jit no-growth gate
on the serving decode dispatch.

Mesh shape: the 8 CPU devices as a 2 (node/DCN) x 4 (chip/ICI)
hierarchy — ``dp`` plays the DCN axis, ``tp`` the ICI axis, matching
the canonical outermost-DCN convention (docs/build.md).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.layers import ep_moe
from triton_dist_tpu.models import Engine, ModelConfig, qwen_moe
from triton_dist_tpu.ops.ep_a2a import (EP2DContext, create_ep_context,
                                        create_ep2d_context)
from triton_dist_tpu.ops.ll_a2a_2d import (hop_put_counts, ll_a2a_2d,
                                           record_dispatch_puts)
from triton_dist_tpu.ops.low_latency import wire_roundtrip
from triton_dist_tpu.parallel.mesh import MeshContext
from triton_dist_tpu.resilience import faults
from triton_dist_tpu.serving import ServingEngine

N_OUT, N_IN = 2, 4
N = N_OUT * N_IN
CFG = ModelConfig.tiny_moe(vocab_size=64, hidden_size=32,
                           num_hidden_layers=2, num_attention_heads=4,
                           num_key_value_heads=4, head_dim=8,
                           num_experts=8, num_experts_per_tok=2,
                           moe_intermediate_size=16)
PAGE = 8
PROMPTS = [[3, 5, 7], [11, 2]]
GEN = 4


@pytest.fixture(scope="module")
def hier_mesh():
    """The 2 (DCN) x 4 (ICI) hierarchy over all 8 devices."""
    return Mesh(np.array(jax.devices()).reshape(N_OUT, N_IN),
                ("dp", "tp"))


@pytest.fixture(scope="module")
def hier_ctx(hier_mesh):
    return MeshContext.from_mesh(hier_mesh)


def _skewed(params):
    """Every routed assignment onto expert 0/1/2 — all owned by node
    0's chips at 8 experts over 8 ranks (the ±pair router trick from
    tests/test_ep_serving.py): maximal cross-node imbalance."""
    p = jax.tree.map(lambda x: x, params)
    rng = np.random.RandomState(0)
    for lp in p["layers"]:
        d, e = lp["moe"]["router"].shape
        g = rng.randn(d).astype(np.float32)
        r = np.zeros((d, e), np.float32)
        r[:, 0] = g
        r[:, 1] = -g
        lp["moe"]["router"] = jnp.asarray(r)
    return p


# ---------------------------------------------------------------------------
# ll_a2a_2d: hop semantics vs the flat wire reference
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("wire", ["int8", "float8_e4m3fn"])
def test_ll_a2a_2d_matches_flat_wire_reference(hier_mesh, hier_ctx,
                                               wire):
    """The 2-hop composition delivers EXACTLY the flat ll_a2a contract
    (out[g'] on rank m = x_{g'}[m], outer-major ranks) up to the
    second wire quantization — compared against a per-chunk
    double-``wire_roundtrip`` oracle, which IS the 2-hop numerics."""
    wire_dtype = jnp.dtype(wire)
    c, d = 6, 16
    rng = np.random.RandomState(1)
    x_all = rng.randn(N, N, c, d).astype(np.float32)  # [src][dst]

    got = jax.jit(jax.shard_map(
        lambda xs: ll_a2a_2d(xs, ctx=hier_ctx, outer_axis="dp",
                             inner_axis="tp", wire_dtype=wire_dtype),
        mesh=hier_mesh, in_specs=P(("dp", "tp"), None, None),
        out_specs=P(("dp", "tp"), None, None), check_vma=False))(
            jnp.asarray(x_all.reshape(N * N, c, d)))
    got = np.asarray(got).reshape(N, N, c, d)

    def wire2(v):
        v1 = wire_roundtrip(jnp.asarray(v), wire_dtype)
        return np.asarray(wire_roundtrip(v1, wire_dtype))

    want = np.stack([np.stack([wire2(x_all[g][m]) for g in range(N)])
                     for m in range(N)])
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("shape", [(1, 8), (8, 1)])
def test_ll_a2a_2d_kernel_hop_degenerate_hierarchy(shape):
    """Degenerate 1×n / n×1 hierarchies keep ONE non-trivial axis, so
    the Pallas kernel hop runs under interpret (the genuine-2D CPU
    case degrades to the identical-numerics xla hop — _resolve_impl).
    The non-trivial hop must match flat ll_a2a wire numerics with the
    trivial hop's extra wire_roundtrip applied."""
    from triton_dist_tpu.ops.low_latency import ll_a2a

    n_out, n_in = shape
    mesh = Mesh(np.array(jax.devices()).reshape(n_out, n_in),
                ("dp", "tp"))
    mctx = MeshContext.from_mesh(mesh)
    c, d = 4, 16
    rng = np.random.RandomState(2)
    x_all = rng.randn(N, N, c, d).astype(np.float32)
    xs = jnp.asarray(x_all.reshape(N * N, c, d))
    spec = P(("dp", "tp"), None, None)

    got = jax.jit(jax.shard_map(
        lambda v: ll_a2a_2d(v, ctx=mctx, outer_axis="dp",
                            inner_axis="tp"),
        mesh=mesh, in_specs=spec, out_specs=spec,
        check_vma=False))(xs)
    flat_axis = "tp" if n_in > 1 else "dp"
    want = jax.jit(jax.shard_map(
        lambda v: wire_roundtrip(
            ll_a2a(v, ctx=mctx, axis=flat_axis), jnp.int8),
        mesh=mesh, in_specs=spec, out_specs=spec,
        check_vma=False))(xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------------
# fwd_decode: ll2d vs the "ar" oracle (uniform + skew, int8 + fp8)
# ---------------------------------------------------------------------------

def _decode_out(hier_mesh, ctx2d, params, x, transport):
    axis = ("dp", "tp")
    specs = ep_moe.param_specs(axis)
    f = jax.jit(jax.shard_map(
        lambda p, v: ep_moe.fwd_decode(
            p, v, topk=CFG.num_experts_per_tok, axis=axis,
            transport=transport, ep_ctx=ctx2d),
        mesh=hier_mesh, in_specs=(specs, P(None, None)),
        out_specs=P(None, None), check_vma=False))
    return np.asarray(f(params, x))


@pytest.mark.parametrize("routing", ["uniform", "skew"])
@pytest.mark.parametrize("wire", ["int8", "float8_e4m3fn"])
def test_fwd_decode_ll2d_matches_ar(hier_mesh, hier_ctx, routing,
                                    wire):
    """The 2-hop dispatch reproduces the zero-communication "ar"
    oracle within the double-wire quantization budget, under uniform
    and all-to-one-node skewed routing."""
    ctx2d = create_ep2d_context(hier_ctx,
                                num_experts=CFG.num_experts,
                                topk=CFG.num_experts_per_tok,
                                outer_axis="dp", inner_axis="tp",
                                wire_dtype=jnp.dtype(wire))
    params = ep_moe.init(jax.random.PRNGKey(3), CFG)
    if routing == "skew":
        d, e = np.asarray(params["router"]).shape
        g = np.random.RandomState(4).randn(d).astype(np.float32)
        r = np.zeros((d, e), np.float32)
        r[:, 0] = g
        r[:, 1] = -g
        params = dict(params, router=jnp.asarray(r))
    x = jax.random.normal(jax.random.PRNGKey(5), (4, CFG.hidden_size),
                          jnp.float32)
    ar = _decode_out(hier_mesh, ctx2d, params, x, "ar")
    ll2d = _decode_out(hier_mesh, ctx2d, params, x, "ll2d")
    # fp8 e4m3 has 3 mantissa bits and the token crosses the wire
    # twice — same budget as test_ep_moe's double-quantization gate.
    tol = 1e-1 if wire == "float8_e4m3fn" else 2e-2
    np.testing.assert_allclose(ll2d, ar, rtol=tol, atol=tol)


def test_fwd_decode_ll2d_needs_2d_context(hier_mesh, hier_ctx):
    params = ep_moe.init(jax.random.PRNGKey(6), CFG)
    x = jnp.zeros((2, CFG.hidden_size), jnp.float32)
    with pytest.raises(ValueError, match="EP2DContext"):
        ep_moe.fwd_decode(params, x, topk=2, transport="ll2d",
                          ep_ctx=None)
    ctx2d = create_ep2d_context(hier_ctx, num_experts=8, topk=2,
                                outer_axis="dp", inner_axis="tp")
    with pytest.raises(ValueError, match="replica"):
        ep_moe.fwd_decode(params, x, topk=2, transport="ll2d",
                          ep_ctx=ctx2d,
                          replicas={"slot_expert": jnp.zeros((1,))})


# ---------------------------------------------------------------------------
# DCN put coalescing: ASSERTED from the trace-time ledger
# ---------------------------------------------------------------------------

def test_dcn_puts_counted_per_peer_node(hier_mesh, hier_ctx):
    """One dispatch issues n_out-1 DCN payload puts (peer NODES), not
    (n_out-1)·n_in (peer chips): the coalescing the tentpole claims,
    read off the put ledger of an actual dispatch trace."""
    ctx2d = create_ep2d_context(hier_ctx,
                                num_experts=CFG.num_experts,
                                topk=CFG.num_experts_per_tok,
                                outer_axis="dp", inner_axis="tp")
    params = ep_moe.init(jax.random.PRNGKey(7), CFG)
    x = jnp.zeros((4, CFG.hidden_size), jnp.float32)
    axis = ("dp", "tp")
    specs = ep_moe.param_specs(axis)
    with record_dispatch_puts() as led:
        jax.eval_shape(
            lambda p, v: jax.shard_map(
                lambda pp, vv: ep_moe.fwd_decode(
                    pp, vv, topk=CFG.num_experts_per_tok, axis=axis,
                    transport="ll2d", ep_ctx=ctx2d),
                mesh=hier_mesh, in_specs=(specs, P(None, None)),
                out_specs=P(None, None), check_vma=False)(p, v),
            params, x)
    # fwd_decode = dispatch + return hop: two ll_a2a_2d calls, each
    # one ICI + one DCN hop.
    dcn = [e for e in led if e["hop"] == "dcn"]
    ici = [e for e in led if e["hop"] == "ici"]
    assert len(dcn) == 2 and len(ici) == 2, led
    analytic = hop_put_counts(hier_ctx, outer_axis="dp",
                              inner_axis="tp")
    for e in dcn:
        assert e["payload_puts"] == N_OUT - 1 == analytic["dcn"]
        # The flat-ll DCN cost this replaces: one put per peer CHIP.
        assert analytic["flat_dcn"] == (N_OUT - 1) * N_IN
        assert e["payload_puts"] * N_IN == analytic["flat_dcn"]
    for e in ici:
        assert e["payload_puts"] == N_IN - 1 == analytic["ici"]


# ---------------------------------------------------------------------------
# per-hop fault containment
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("op", ["ll2d_ici", "ll2d_dcn"])
def test_fault_containment_per_hop(hier_mesh, hier_ctx, op):
    """Dropping either hop fails THAT dispatch with the hop's own op
    name (scoped faults.on_op_call), and the next dispatch outside the
    plan succeeds — one lost dispatch, not a dead server."""
    ctx2d = create_ep2d_context(hier_ctx,
                                num_experts=CFG.num_experts,
                                topk=CFG.num_experts_per_tok,
                                outer_axis="dp", inner_axis="tp")
    params = ep_moe.init(jax.random.PRNGKey(8), CFG)
    x = jnp.ones((2, CFG.hidden_size), jnp.float32)

    def trace_once():
        return _decode_out(hier_mesh, ctx2d, params, x, "ll2d")

    with faults.inject(faults.get_plan("fail_kth_call", op=op, k=0)):
        with pytest.raises(faults.InjectedFault) as ei:
            trace_once()
        assert op in str(ei.value)   # the fault names the hop
    out = trace_once()               # the server survives the fault
    assert np.isfinite(out).all()


# ---------------------------------------------------------------------------
# serving: token exactness + observability + jit no-growth
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def hier_engines(hier_mesh):
    base = qwen_moe.init_params(jax.random.PRNGKey(0), CFG)
    params = {"uniform": base, "skew": _skewed(base)}
    cache = {}

    def get(routing: str) -> Engine:
        if routing not in cache:
            cache[routing] = Engine(CFG, hier_mesh, mode="xla",
                                    max_len=32, model=qwen_moe,
                                    moe_impl="ep",
                                    ep_axis=("dp", "tp"),
                                    params=params[routing])
        return cache[routing]

    return get


@pytest.mark.parametrize("routing", ["uniform", "skew"])
def test_serving_ll2d_token_exact_and_observable(hier_engines,
                                                 routing):
    """Greedy decode through the 2-hop dispatch is TOKEN-EXACT vs the
    "ar" serve on the same hierarchical engine; the resolved transport
    is observable in stats; the decode dispatch never re-specializes;
    and the unset-knob default resolves to ll2d — the fallback is
    dead, not hidden."""
    eng = hier_engines(routing)
    want = ServingEngine(eng, num_slots=2, page=PAGE,
                         transport="ar").generate(
        PROMPTS, max_new_tokens=GEN)

    srv = ServingEngine(eng, num_slots=2, page=PAGE, transport="ll2d")
    got = srv.generate(PROMPTS, max_new_tokens=GEN)
    assert got == want
    assert srv.stats()["dispatch_transport"] == "ll2d"
    assert srv.decode_cache_size() <= 2   # PR-4 fixed-shape gate

    # transport unset -> "auto" -> untuned hierarchical mesh -> ll2d.
    auto = ServingEngine(eng, num_slots=2, page=PAGE)
    assert auto.generate(PROMPTS, max_new_tokens=GEN) == want
    assert auto.stats()["dispatch_transport"] == "ll2d"


def test_serving_ll2d_rejects_replicas(hier_engines):
    with pytest.raises(ValueError, match="replica"):
        ServingEngine(hier_engines("uniform"), num_slots=2, page=PAGE,
                      transport="ll2d", replica_slots=1)


# ---------------------------------------------------------------------------
# 2D-keyed tune round-trip
# ---------------------------------------------------------------------------

def test_tune_transport_2d_roundtrip(hier_mesh, hier_ctx, tmp_path,
                                     monkeypatch):
    """On a hierarchical mesh ``tune_transport`` sweeps ar vs ll2d,
    persists the winner under the hierarchy-shaped key, ``"auto"``
    resolution loads it back — and the 2D key can never collide with
    a flat-mesh key of the same total size."""
    from triton_dist_tpu import tune

    monkeypatch.setenv("TRITON_DIST_TPU_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(tune, "_CACHE", None)
    monkeypatch.setattr(tune, "_CACHE_PATH", None)

    ctx2d = create_ep2d_context(hier_ctx,
                                num_experts=CFG.num_experts,
                                topk=CFG.num_experts_per_tok,
                                outer_axis="dp", inner_axis="tp")
    params = ep_moe.init(jax.random.PRNGKey(9), CFG)
    kw = dict(ctx=ctx2d, batch=2, hidden=CFG.hidden_size,
              dtype=jnp.float32, topk=CFG.num_experts_per_tok)
    # Untuned hierarchical mesh: ll2d, NOT the old "ar" fallback.
    assert ep_moe.resolve_transport("auto", **kw) == "ll2d"
    winner = ep_moe.tune_transport(hier_mesh, params, ctx2d, batch=2,
                                   topk=CFG.num_experts_per_tok,
                                   reps=1)
    assert winner in ("ar", "ll2d")
    assert ep_moe.resolve_transport("auto", **kw) == winner
    # cache hit (no re-timing)
    assert ep_moe.tune_transport(
        hier_mesh, params, ctx2d, batch=2,
        topk=CFG.num_experts_per_tok) == winner
    # forced store wins over timing noise
    forced = "ar" if winner == "ll2d" else "ll2d"
    tune.store_autotune_data(
        ep_moe._transport_key(ctx2d, batch=2, hidden=CFG.hidden_size,
                              dtype=np.dtype("float32"),
                              topk=CFG.num_experts_per_tok),
        {"transport": forced})
    assert ep_moe.resolve_transport("auto", **kw) == forced
    # Hierarchy shape is IN the key: flat and 2D contexts over the
    # same 8 devices key differently.
    flat = create_ep_context(hier_ctx, num_experts=CFG.num_experts,
                             topk=CFG.num_experts_per_tok, axis="tp")
    k2d = ep_moe._transport_key(ctx2d, batch=2,
                                hidden=CFG.hidden_size,
                                dtype=jnp.float32,
                                topk=CFG.num_experts_per_tok)
    kflat = ep_moe._transport_key(flat, batch=2,
                                  hidden=CFG.hidden_size,
                                  dtype=jnp.float32,
                                  topk=CFG.num_experts_per_tok)
    assert k2d != kflat


# ---------------------------------------------------------------------------
# megakernel expert counts with chunked prefill (PR 6 known limit)
# ---------------------------------------------------------------------------

def test_mk_expert_counts_with_chunked_prefill():
    """The ``moe_counts`` arena region is now engine-wide (same
    offset AND rows in every builder sharing the arena), so
    ``expert_counts()`` stays correct — monotonic, consistent with
    the decode telemetry — with chunked prefill active. Under the old
    layout the chunk builder's activation tail aliased the decode
    builder's counters."""
    from triton_dist_tpu.megakernel.engine import MegaKernelEngine

    cfg = ModelConfig.tiny_moe(vocab_size=64, hidden_size=32,
                               num_hidden_layers=2,
                               num_attention_heads=4,
                               num_key_value_heads=4, head_dim=8,
                               num_experts=4, num_experts_per_tok=2,
                               moe_intermediate_size=32)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    buckets = (4, 8)
    mk = MegaKernelEngine(cfg, mesh, batch=2, max_len=64, tile_w=16,
                          t_tile=16, paged=True, page=16, num_pages=9,
                          prefill_buckets=buckets)
    # Every builder claims the SAME counter span.
    dec_reg = mk.builder.schema.region("moe_counts")
    for cb in mk.chunk_builders.values():
        reg = cb.schema.region("moe_counts")
        assert (reg.offset, reg.rows) == (dec_reg.offset, dec_reg.rows)
    assert dec_reg.rows >= max(buckets)

    srv = ServingEngine(mk, prefill_buckets=buckets)
    prompts = [[int(t) for t in
                np.random.RandomState(s).randint(1, 64, 7)]
               for s in (0, 1)]
    c0 = mk.expert_counts()
    srv.generate(prompts, max_new_tokens=3)
    c1 = mk.expert_counts()
    # Counters accumulated routed assignments (prefill chunks AND
    # decode steps) and stayed monotonic + bounded by the routed-row
    # budget: rows * topk * n_layers per launch.
    assert (c1 >= c0).all() and c1.sum() > c0.sum()
    assert c1.sum() % (cfg.num_experts_per_tok
                       * cfg.num_hidden_layers) == 0
    srv.generate(prompts, max_new_tokens=2)
    c2 = mk.expert_counts()
    assert (c2 >= c1).all() and c2.sum() > c1.sum()
