# Top-level targets (reference: .github/workflows/amd-ci.yml battery).

PY ?= python

.PHONY: csrc test quick race verify-faults bench-smoke bench-megakernel \
	serve-smoke ep-smoke ep2d-smoke aggemm-smoke disagg-smoke \
	spec-smoke chaos-smoke \
	qblock-smoke obs-smoke tier-smoke fleet-smoke slo-smoke \
	mega-parity-smoke mkchunk-smoke supervise-smoke apicheck ci \
	bench-all

csrc:
	$(MAKE) -C csrc

# PYTEST_ARGS lets CI deselect files covered by dedicated jobs
# (e.g. --ignore=tests/test_multihost.py).
test: csrc
	$(PY) -m pytest tests/ -x -q $(PYTEST_ARGS)

# Sub-2-minute smoke tier for iteration (primitives, collectives,
# low-latency family, tools; the full battery stays the merge gate).
quick: csrc
	$(PY) -m pytest tests/test_shmem.py tests/test_tools.py \
	    tests/test_low_latency.py tests/test_collectives.py -x -q

# The whole battery under the vector-clock race detector — the
# deliberate signal-protocol checker (SURVEY.md section 5).
race: csrc
	TRITON_DIST_TPU_DETECT_RACES=1 $(PY) -m pytest \
	    tests/test_shmem.py tests/test_collectives.py -x -q

# Fault battery: tier-1 plus tests/test_resilience.py under the race
# detector on the CPU mesh (docs/resilience.md).
verify-faults: csrc
	bash scripts/verify_faults.sh

# Overlap-schedule smoke: swizzle/prefetch parity sweep + interpret-mode
# bench on the CPU mesh — verify-faults' perf sibling (docs/perf.md).
bench-smoke: csrc
	bash scripts/bench_smoke.sh

# Megakernel scheduler battery: dynamic-vs-static token-exactness on the
# CPU mesh + interpret-mode bench with non-null megakernel values
# (docs/megakernel.md, dynamic scoreboard scheduler).
bench-megakernel: csrc
	bash scripts/bench_megakernel.sh

# Serving battery: continuous batching + streaming chat server on the
# CPU mesh, gated on per-request token-exactness vs Engine.serve and
# the fixed-decode-shape jit-cache check (docs/serving.md).
serve-smoke: csrc
	bash scripts/serve_smoke.sh

# EP serving battery: skewed-routing token-exactness across decode
# transports on the CPU mesh + a non-null bench.py ep_dispatch_ms gate
# (docs/serving.md EP-decode section).
ep-smoke: csrc
	bash scripts/ep_smoke.sh

# Hierarchical EP decode battery: 2-hop ll2d token-exactness + the
# asserted DCN put-coalescing gate on the CPU mesh, a forced-2D-mesh
# chat e2e gating the transport=ll2d exit line, and the non-null
# bench.py ep_dispatch_2d_ms / ep2d_dcn_puts gate (docs/serving.md
# EP-decode hierarchy section).
ep2d-smoke: csrc
	bash scripts/ep2d_smoke.sh

# ag_gemm variant battery: panel/pipelined parity (both real kernels,
# no interpret fallback) across swizzle x depth x sim-ring, wide-K
# host-side schedule math, the variant-autotune round-trip, and the
# non-null bench.py panel/pipelined crossover gate (pipelined must
# stay within 1.1x of panel at block_m <= 512; docs/perf.md).
aggemm-smoke: csrc
	bash scripts/aggemm_smoke.sh

# Disaggregated-serving battery: chunked-prefill bucket gates + page
# migration on the CPU mesh, a split-role chat e2e, and the non-null
# chunked-vs-monolithic bench gate (docs/serving.md disaggregation
# section).
disagg-smoke: csrc
	bash scripts/disagg_smoke.sh

# Quantized-KV + speculative-decode battery: bounded-divergence and
# capacity gates, spec determinism/rollback, a quantized+speculative
# chat e2e, and the non-null spec/quant bench-key gate
# (docs/serving.md quantization + speculation sections).
spec-smoke: csrc
	bash scripts/spec_smoke.sh

# Fault-tolerance battery: retry/backoff + failover + checkpoint/
# restore units, the seeded 200-tick chaos acceptance soak (invariant
# checker every tick, survivors token-exact vs the fault-free oracle),
# a chat-server kill/resume e2e, and the non-null
# chaos_survived_faults bench gate (docs/resilience.md).
chaos-smoke: csrc
	bash scripts/chaos_smoke.sh

# Paged flash Q-block battery: kernel-vs-gather-oracle parity across
# pool dtypes, flash-path chunk/verify token-exactness + no-recompile
# gates, a flash chat e2e, and the non-null flash<=ref bench gate on
# chunk_attend_ms/verify_attend_ms (docs/serving.md, "Attention
# implementations").
qblock-smoke: csrc
	bash scripts/qblock_smoke.sh

# Observability battery: span-timeline determinism under a fake clock,
# histogram/percentile units, telemetry bit-exactness + no-growth
# gates, and a traced chat e2e gating the merged Perfetto file and the
# one-line `obs:` latency summary (docs/observability.md).
obs-smoke: csrc
	bash scripts/obs_smoke.sh

# Tiered-KV battery: tier-store/scored-eviction units, park/resume
# token-exactness, tier coherence under chaos, the heavy-tailed
# multi-turn trace, a parked-and-resumed chat e2e gating the `tiers:`
# exit-summary line, and the non-null kv_hot_hit_rate /
# session_resume_ms / offloaded_pages bench gate (docs/serving.md,
# "KV memory hierarchy").
tier-smoke: csrc
	bash scripts/tier_smoke.sh

# Fleet-serving battery: affinity routing vs round-robin, cross-fleet
# failover token-exactness (parked-tier handoff + re-prefill),
# drain/restore autoscale, shed-by-deadline-class, the fleet chaos
# soak, an R=2 chat e2e with a mid-serve fleet kill gating
# bit-identical token streams, and the non-null fleet_p99_ttft_ms /
# fleet_failover_resumed / fleet_shed_requests /
# router_affinity_hit_rate bench gate (docs/serving.md, "Fleet
# serving").
fleet-smoke: csrc
	bash scripts/fleet_smoke.sh

# Multi-tenant SLO battery: EDF/DRR/aging units on a fake clock,
# per-tenant backpressure + decode quotas, preemption token-exactness
# through both eviction paths, the noisy-neighbor isolation gate, the
# router's class/over-quota shed order, the multi-tenant chaos soak,
# a bit-identical-streams chat e2e with --slo --tenants 2, and the
# non-null slo_attainment / tenant_interactive_p99_ttft_ms /
# slo_preemptions bench gate (>= 2x interactive isolation at >= 0.8x
# bulk throughput; docs/serving.md, "Multi-tenant SLO scheduling").
slo-smoke: csrc
	bash scripts/slo_smoke.sh

# Megakernel serving-parity battery: quantized-KV token agreement +
# capacity gates, Q-block speculation token-exact vs the non-spec mk
# run, schema checkpoint/restore resuming mid-stream, a
# bit-identical-streams chat e2e with --megakernel --kv-quant int8
# --spec, and the non-null megakernel_decode_quant_ms /
# megakernel_tokens_per_s_spec bench gate (docs/megakernel.md,
# "Arena schema").
mega-parity-smoke: csrc
	bash scripts/mega_parity_smoke.sh

# Megakernel chunked-prefill battery: bucket-edge token-exactness vs
# the one-token lane and the layer ChunkedPrefill, quantized chunk
# writes, prefix-hit skip of resident pages, the chunk-step no-growth
# gates, a bit-identical-streams chat e2e with --megakernel
# --mk-chunked, and the non-null megakernel_prefill_chunk_ms /
# megakernel_tokens_per_s_prefill_heavy (>= 2x one-token lane) bench
# gate (docs/megakernel.md, "Chunked prefill").
mkchunk-smoke: csrc
	bash scripts/mkchunk_smoke.sh

# Supervised-serving battery: checkpoint-envelope + keep-last-K ring
# corruption fallback, parent-side ack dedupe/divergence/gap units,
# real-child crash + stall recovery token-exact, the three-boundary
# payload-integrity drill (tier put / migration send / fleet
# handoff), the >= 6-fault supervised soak, a SIGKILL-mid-stream
# crash/resume e2e, and the non-null crash_recovery_ms /
# supervised_survived_faults / integrity_checks bench gate
# (docs/resilience.md, "Process supervision").
supervise-smoke: csrc
	bash scripts/supervise_smoke.sh

# docs/api.md is generated; fail CI when it drifts from the source.
apicheck:
	$(PY) -m triton_dist_tpu.tools.gen_api --check

ci: test race apicheck

# Hardware battery: every fused op once on the real chip (needs a TPU).
bench-all:
	$(PY) bench.py --all
