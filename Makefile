# Top-level targets (reference: .github/workflows/amd-ci.yml battery).

PY ?= python

.PHONY: csrc test race ci bench-all

csrc:
	$(MAKE) -C csrc

test: csrc
	$(PY) -m pytest tests/ -x -q

# The whole battery under the vector-clock race detector — the
# deliberate signal-protocol checker (SURVEY.md section 5).
race: csrc
	TRITON_DIST_TPU_DETECT_RACES=1 $(PY) -m pytest \
	    tests/test_shmem.py tests/test_collectives.py -x -q

ci: test race

# Hardware battery: every fused op once on the real chip (needs a TPU).
bench-all:
	$(PY) bench.py --all
