"""Benchmark entry point — prints ONE JSON line.

Metric: AG+GEMM overlap efficiency versus compute-only GEMM (the
north-star from BASELINE.json: >=0.90 of compute-only on a TP mesh).

- With >=2 real TPU chips: the full measurement — overlapped
  ``ag_gemm`` wall time vs (pure XLA dot on pre-gathered A).
- With 1 chip (current axon tunnel): the SELF-SIMULATED RING — A is
  split into SIM_RANKS chunks and the full multi-chip ring schedule
  runs with self-targeted RDMA puts (``ag_gemm(sim_ranks=8)``):
  identical control flow, semaphore waits, staging, and per-step
  compute:comm ratio; only the wire is HBM instead of ICI. Strictly
  harder than the round-1..3 rankless-pipeline proxy (which skipped
  the ring entirely); that older number is still reported in
  ``detail.rankless_kernel_efficiency`` for continuity.

Timing: the axon tunnel acks dispatches early and carries a large fixed
RTT, so each measurement runs dependency-chained iterations inside one
jit (a numerically *visible* bump keeps XLA from hoisting the op out of
the loop), fetches the result (forcing device completion), and takes the
slope between two chain lengths — the fixed RTT cancels exactly.

``vs_baseline`` is value / 0.90 (the reference-implied H800 target).
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import time

import numpy as np

ITERS_LO, ITERS_HI = 8, 72
ITERS_HI_FINAL = 200   # long final chains: slope error ~ noise / (hi-lo)
REPEATS = 5
SWEEP_REPEATS = 3

# Self-simulated ring size for the single-chip overlap measurement
# (chunks = the v5p-8 TP degree the kernels are designed for).
SIM_RANKS = 8

# Config space swept at bench time (ADVICE r1: a single hardcoded config
# left the metric at the mercy of one noise sample). The round-1 winner
# leads; the others bracket it in block_n / block_k, plus the pipelined
# (BlockSpec-A) variant at both granularities.
AG_GEMM_CONFIGS = (
    {"block_m": 1024, "block_n": 128, "block_k": 4096},
    {"block_m": 1024, "block_n": 256, "block_k": 4096},
    {"block_m": 512, "block_n": 128, "block_k": 4096},
    {"block_m": 1024, "block_n": 128, "block_k": 2048},
    {"block_m": 256, "block_n": 512, "block_k": 1024},
    # Double-buffered panels (block_m <= 512 fits two (tm, K) panels in
    # the VMEM budget): the cross-chunk prefetch path — no cold panel
    # load or arrival stall at ring boundaries (r5 kernel change).
    {"block_m": 512, "block_n": 256, "block_k": 4096},
    {"block_m": 256, "block_n": 128, "block_k": 4096},
    {"variant": "pipelined", "block_m": 256, "block_n": 256,
     "block_k": 1024},
    {"variant": "pipelined", "block_m": 128, "block_n": 512,
     "block_k": 2048},
    # Variant-crossover pairs: both variants measured at block_m
    # {128, 256, 512} so the panel-vs-streamed crossover is read off
    # ONE sweep (detail.ag_gemm_variant_crossover), not stitched from
    # different rounds.
    {"block_m": 128, "block_n": 256, "block_k": 4096},
    {"variant": "pipelined", "block_m": 512, "block_n": 256,
     "block_k": 1024},
)

# gemm_rs gets the same treatment (round-1 winner first): its detail
# number rode a single hardcoded config and drifted with tunnel noise.
GEMM_RS_CONFIGS = (
    {"block_m": 1024, "block_n": 128, "block_k": 4096},
    {"block_m": 512, "block_n": 128, "block_k": 4096},
    # NOT 1024x256x4096: 20 MB scoped VMEM > the 16 MB limit — it can
    # OOM asynchronously mid-sweep where the skip-on-compile-failure
    # policy cannot catch it.
    {"block_m": 512, "block_n": 128, "block_k": 2048},
)


def _make_chain(step, iters):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chain(a, b):
        def body(_, a):
            out = step(a, b)
            # Visible scalar bump: forces true sequential execution
            # (an invisible-in-bf16 bump lets XLA hoist the op).
            bump = (out.reshape(-1)[0].astype(jnp.float32) * 1e-3
                    ).astype(a.dtype)
            return jnp.clip(a + bump, -4.0, 4.0)
        s = jax.lax.fori_loop(0, iters, body, a)
        return jnp.sum(s.astype(jnp.float32))
    return chain


def _timed_chain(step, a, b, repeats=REPEATS):
    """step: (a, b) -> out; returns seconds/iter via two-point slope."""
    times = {}
    for iters in (ITERS_LO, ITERS_HI):
        chain = _make_chain(step, iters)
        v = np.asarray(chain(a, b))  # warmup/compile
        assert np.isfinite(v), "benchmark chain produced non-finite value"
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            np.asarray(chain(a, b))
            best = min(best, time.perf_counter() - t0)
        times[iters] = best
    return (times[ITERS_HI] - times[ITERS_LO]) / (ITERS_HI - ITERS_LO)


def _timed_chain_group(entries, repeats=REPEATS, lo=ITERS_LO,
                       hi=ITERS_HI_FINAL):
    """Interleaved slope timing for a group of steps.

    entries: {name: (step, a, b)} -> {name: seconds/iter}. Every repeat
    samples EVERY chain back-to-back, so slow phases of the tunnel (or
    the chip) hit numerator and denominator alike — the round-1 failure
    mode was sequential timing letting drift between two measurements
    swing the efficiency ratio +-15%.
    """
    chains = {}
    for name, (step, a, b) in entries.items():
        per = {}
        for iters in (lo, hi):
            c = _make_chain(step, iters)
            v = np.asarray(c(a, b))  # warmup/compile
            assert np.isfinite(v), f"chain {name!r} produced non-finite"
            per[iters] = c
        chains[name] = per
    best = {name: {lo: float("inf"), hi: float("inf")}
            for name in entries}
    for _ in range(repeats):
        for name, (step, a, b) in entries.items():
            for iters in (lo, hi):
                t0 = time.perf_counter()
                np.asarray(chains[name][iters](a, b))
                dt = time.perf_counter() - t0
                best[name][iters] = min(best[name][iters], dt)
    return {name: (best[name][hi] - best[name][lo]) / (hi - lo)
            for name in entries}


def _last_result_path() -> str:
    """Last successful bench result, persisted OUTSIDE the jax-version-
    stamped tune cache (reading it must not touch a backend)."""
    base = os.environ.get(
        "TRITON_DIST_TPU_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "triton_dist_tpu"))
    os.makedirs(base, exist_ok=True)
    return os.path.join(base, "bench_last.json")


def _load_last_result():
    """Best stale result available: this machine's last successful run,
    else the newest committed BENCH_r*.json with a parsed payload."""
    try:
        with open(_last_result_path()) as f:
            return json.load(f), "local_cache"
    except (FileNotFoundError, json.JSONDecodeError):
        pass
    here = os.path.dirname(os.path.abspath(__file__))
    for p in sorted(glob.glob(os.path.join(here, "BENCH_r*.json")),
                    reverse=True):
        try:
            with open(p) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        parsed = rec.get("parsed") if isinstance(rec, dict) else None
        if (isinstance(parsed, dict) and parsed.get("value") is not None
                and not (parsed.get("detail") or {}).get(
                    "backend_unavailable")):
            # Only genuine measurements: a stale-replay record would
            # chain staleness without ever having touched hardware.
            return parsed, os.path.basename(p)
    return None, None


def _probe_cache_path() -> str:
    return os.path.join(os.path.dirname(_last_result_path()),
                        "backend_probe.json")


def _partials_path() -> str:
    """Per-config partial sweep results, persisted AS MEASURED so a
    mid-sweep death (hung Mosaic compile, tunnel drop, hard kill)
    still yields data — the BENCH_r02–r05 stale-copy pattern's fix:
    the next run (or the stale-fallback record) salvages whatever
    configs completed."""
    return os.path.join(os.path.dirname(_last_result_path()),
                        "bench_partials.json")


def _load_partials():
    try:
        with open(_partials_path()) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _note_partial(name: str, cfg, seconds: float) -> None:
    """Append one swept config's timing to the partials file (load-
    modify-replace; bench sweeps are single-process)."""
    rec = _load_partials() or {"started_at_unix": int(time.time()),
                               "sweeps": {}}
    rec["sweeps"].setdefault(name, []).append(
        {"config": cfg, "ms": round(seconds * 1e3, 3)})
    rec["updated_at_unix"] = int(time.time())
    tmp = _partials_path() + f".tmp{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, _partials_path())
    except OSError:
        pass
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _clear_partials() -> None:
    try:
        os.remove(_partials_path())
    except OSError:
        pass


# How many probe subprocesses the LAST _probe_backend call launched
# (tier-0 smoke + tier-1 retries) — surfaced as detail.probe_attempts
# so a record shows whether bring-up was clean or fought the tunnel.
_PROBE_ATTEMPTS = 0


def _probe_backend(budget_s: float, backoff_s: float) -> str | None:
    """Retry backend bring-up in SUBPROCESSES (jax caches a failed
    backend for the life of the process, so in-process retries are
    no-ops). Returns None on success, else the last error string.
    Wall-clock budgeted, not attempt-counted: a down tunnel makes each
    probe HANG to its timeout rather than fail fast.

    Round-2 failure mode this guards: the axon TPU tunnel was down at
    bench time, ``jax.devices()`` raised once, and the whole round
    recorded rc=1 with nothing measured (VERDICT r2 weak #1).

    BENCH_r05 failure mode this guards: a CPU-ONLY container ate a
    240 s probe timeout (and would have retried to the full budget)
    before falling back. Three fixes: the per-attempt timeout is capped
    at BENCH_PROBE_TIMEOUT_S (default 30 s); a probe that COMPLETES and
    reports only-CPU devices is a definite verdict — a CPU container
    will not grow a TPU, so it short-circuits the retry loop; and the
    verdict is cached (BENCH_PROBE_TTL_S, default 3600 s) so reruns
    skip the probe entirely. ``BENCH_BACKEND=cpu|tpu`` forces the
    verdict with no probe at all."""
    forced = os.environ.get("BENCH_BACKEND")
    if forced == "tpu":
        return None
    if forced == "cpu":
        return "cpu-only (forced via BENCH_BACKEND)"
    ttl = float(os.environ.get("BENCH_PROBE_TTL_S", "3600"))
    if ttl > 0:
        try:
            with open(_probe_cache_path()) as f:
                cached = json.load(f)
            if (cached.get("error") is not None
                    and time.time() - cached.get("checked_at", 0) < ttl):
                return cached.get("error")
        except (OSError, json.JSONDecodeError, TypeError):
            pass

    def _remember(error):
        # ONLY the definite cpu-only verdict is cacheable: a CPU
        # container will not grow a TPU within the TTL, but a present
        # TPU (or a transient tunnel error) can change state between
        # runs — replaying those would crash a later run in-process
        # (TPU-present cached, tunnel since dropped) or extend an
        # outage verdict past the outage.
        if error is not None and error.startswith("cpu-only"):
            try:
                with open(_probe_cache_path(), "w") as f:
                    json.dump({"checked_at": time.time(), "error": error},
                              f)
            except OSError:
                pass
        return error

    probe_cap = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "30"))

    def _attempt(code: str, timeout_s: float):
        """One probe subprocess → ("ok"|"cpu"|"retry", error|None).
        The axon plugin pins jax_platforms="axon,cpu": a failed TPU
        init can fall back to CPU, which would pass a bare device-count
        probe and then "measure" Mosaic kernels on the CPU backend.
        Require a non-CPU device — but report a completed CPU-only
        probe distinctly from a crash."""
        global _PROBE_ATTEMPTS
        _PROBE_ATTEMPTS += 1
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=timeout_s)
        except subprocess.TimeoutExpired:
            return "retry", f"probe timeout ({timeout_s:.0f}s)"
        if r.returncode != 0:
            return "retry", (r.stderr.strip().splitlines()
                             or ["unknown"])[-1][:300]
        platform, cfg = "unknown", ""
        for line in r.stdout.splitlines():
            if line.startswith("PLATFORM="):
                platform = line.split("=", 1)[1]
            elif line.startswith("CONFIG="):
                cfg = line.split("=", 1)[1]
        if platform not in ("cpu", "none", "unknown"):
            return "ok", None
        non_cpu = [p for p in cfg.replace(" ", "").split(",")
                   if p and p != "cpu"]
        if non_cpu:
            # A non-CPU platform is configured but init fell back to
            # CPU — a transient tunnel blip, not a definite verdict:
            # keep retrying and never cache it.
            return "retry", (f"configured platform {non_cpu[0]!r} fell "
                             "back to cpu (transient init failure?)")
        # Definite: no non-CPU platform is even configured and the
        # backend came up CPU-only. Retrying cannot change that.
        return "cpu", f"cpu-only backend (platform={platform})"

    _CONFIG = ("import os, jax; "
               "cfg = (jax.config.jax_platforms "
               "       or os.environ.get('JAX_PLATFORMS') or ''); "
               "print('CONFIG=' + cfg); ")
    # Tier 0: ONE TRIVIAL-KERNEL SMOKE with a short deadline before the
    # long device-count probe. A healthy backend compiles and runs an
    # 8x8 reduction in seconds; a wedged tunnel hangs — don't spend the
    # 240 s-class probe budget finding that out (the BENCH_r02-r05
    # failure shape). A definitive smoke verdict (device present and a
    # kernel actually ran, or definitely CPU-only) skips tier 1.
    smoke_cap = float(os.environ.get("BENCH_PROBE_SMOKE_TIMEOUT_S",
                                     "20"))
    smoke_code = (_CONFIG +
                  "import jax.numpy as jnp; "
                  "v = float(jnp.ones((8, 8)).sum()); "
                  "assert v == 64.0, v; "
                  "d = jax.devices(); "
                  "print('PLATFORM=' + (d[0].platform if d else 'none'))")
    verdict, err = _attempt(smoke_code,
                            max(min(smoke_cap, budget_s), 5.0))
    if verdict == "ok":
        return _remember(None)
    if verdict == "cpu":
        return _remember(err)

    # Tier 1: the device-count probe under the full wall-clock budget,
    # retried through the shared RetryPolicy (resilience.policy): a
    # down tunnel gets exponential backoff + seeded jitter across the
    # 900 s budget instead of a fixed-cadence hammer, and one transient
    # probe timeout no longer burns straight to a stale BENCH record.
    from triton_dist_tpu.resilience.policy import RetryPolicy

    class _ProbeRetry(Exception):
        pass

    probe_code = (_CONFIG +
                  "d = jax.devices(); "
                  "print('PLATFORM=' + (d[0].platform if d else 'none'))")
    t_end = time.monotonic() + budget_s

    def one_probe():
        verdict, err = _attempt(
            probe_code,
            max(min(probe_cap, t_end - time.monotonic()), 5.0))
        if verdict == "retry":
            raise _ProbeRetry(err)
        return verdict, err

    policy = RetryPolicy(
        max_attempts=max(int(budget_s / max(backoff_s, 1.0)) + 1, 2),
        base_delay_s=backoff_s, multiplier=1.5,
        max_delay_s=max(backoff_s * 8, backoff_s), jitter=0.25, seed=0)
    try:
        (verdict, err), _ = policy.call(
            one_probe, op="bench.backend_probe",
            retry_on=(_ProbeRetry,), deadline_s=budget_s)
    except _ProbeRetry as e:
        return _remember(str(e) or "probe retries exhausted")
    return _remember(None if verdict == "ok" else err)


def _interpret_megakernel_times() -> dict:
    """Interpret-mode megakernel decode-step timing, static vs dynamic
    schedule side by side (CPU-only hosts previously emitted
    ``value: null`` here — the interpreter executes the REAL scoreboard
    protocol, so the ratio tracks schedule+dispatch overhead, not
    silicon). Also reports each schedule's idle (NOOP) slot count —
    the scoreboard-step metric the dynamic claim scheduler shrinks."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from triton_dist_tpu.megakernel.engine import MegaKernelEngine
    from triton_dist_tpu.models.config import ModelConfig

    cfg = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                           intermediate_size=32, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2,
                           head_dim=8)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    toks = jnp.asarray([1, 2], jnp.int32)
    out = {"megakernel_decode_step_ms": {}, "megakernel_idle_slots": {},
           "megakernel_sim": {}}
    for mode in ("static", "dynamic"):
        eng = MegaKernelEngine(cfg, mesh, batch=2, max_len=32,
                               tile_w=16, t_tile=16, num_cores=2,
                               strategy="cost_lpt", schedule=mode)
        np.asarray(eng.decode_step(toks, 0))     # compile + warmup
        best = float("inf")
        for i in range(2):
            t0 = time.perf_counter()
            np.asarray(eng.decode_step(toks, 1 + i))
            best = min(best, time.perf_counter() - t0)
        out["megakernel_decode_step_ms"][mode] = round(best * 1e3, 3)
        out["megakernel_idle_slots"][mode] = eng.builder.noop_slots()
        out["megakernel_sim"][mode] = {
            "idle_units": eng.builder.idle_units,
            "makespan": eng.builder.makespan}
    return out


def _interpret_mega_parity() -> dict:
    """Megakernel serving parity on the interpret mesh: the paged
    persistent lane's decode-step wall time per kv_dtype (fused
    quantize-on-write / dequantize-on-read vs the fp32 pools) and the
    Q-block speculative tokens/s vs the non-spec lane on the same
    repetitive trace — the serving-speed keys the layer path has had
    since PR 8, now with megakernel values (interpret overhead, not
    silicon; presence + relative shape are the signal)."""
    import jax
    import jax.numpy as jnp  # noqa: F401 — backend warmup
    from jax.sharding import Mesh

    from triton_dist_tpu.megakernel.engine import MegaKernelEngine
    from triton_dist_tpu.models.config import ModelConfig
    from triton_dist_tpu.serving import ServingEngine

    cfg = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                           intermediate_size=32, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2,
                           head_dim=8)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    kw = dict(batch=2, max_len=32, tile_w=16, t_tile=16, paged=True,
              page=16, num_pages=5)

    out = {"megakernel_decode_quant_ms": {},
           "megakernel_tokens_per_s_spec": {}}
    for kvd in ("bf16", "int8", "fp8"):
        mk = MegaKernelEngine(cfg, mesh, kv_dtype=kvd, **kw)
        s = ServingEngine(mk, kv_dtype=kvd)
        s.generate([[1, 2, 3]], max_new_tokens=2)    # compile warmup
        s.submit([4, 5, 6], max_new_tokens=6)
        s.submit([7, 8], max_new_tokens=6)
        n0 = s.stats()["decode_dispatches"]
        t0 = time.perf_counter()
        s.run()
        dt = time.perf_counter() - t0
        n = s.stats()["decode_dispatches"] - n0
        out["megakernel_decode_quant_ms"][kvd] = round(
            dt * 1e3 / max(n, 1), 3)

    # Q-block speculation on/off over the repetitive greedy trace (the
    # workload the n-gram draft wins on): tokens/s including the
    # prefill-lane ticks, plus the accept rate and the one-entry
    # verification jit gate.
    spec_trace = [[1, 2, 3, 1, 2, 3, 1, 2], [7, 8, 7, 8, 7, 8]]
    out["megakernel_spec_accept_rate"] = None
    for name, k in (("nospec", 0), ("spec", 2)):
        mk = MegaKernelEngine(cfg, mesh, spec_k=k,
                              schedule="dynamic" if k else "static",
                              **kw)
        s = ServingEngine(mk, spec_k=k)
        s.generate(spec_trace, max_new_tokens=8)     # compile warmup
        for c in s.stats_counters:
            s.stats_counters[c] = type(s.stats_counters[c])(0)
        t0 = time.perf_counter()
        s.generate(spec_trace, max_new_tokens=16)
        dt = time.perf_counter() - t0
        st = s.stats()
        out["megakernel_tokens_per_s_spec"][name] = round(
            st["tokens_generated"] / max(dt, 1e-9), 2)
        if k:
            out["megakernel_spec_accept_rate"] = (
                None if st["spec"]["accept_rate"] is None
                else round(st["spec"]["accept_rate"], 4))
            assert st["spec"]["tokens_per_dispatch"] > 1.0, (
                "megakernel speculation never amortized a dispatch")
    return out


def _interpret_mega_chunked() -> dict:
    """Megakernel chunked prefill on the interpret mesh: per-chunk
    dispatch wall time plus prefill-heavy tokens/s for the bucketed
    WRITE_KV_CHUNK/ATTN_CHUNK lane vs the one-token-per-tick prefill
    lane on the SAME engine shape and workload (long prompts, two
    generated tokens). Interpret overhead, not silicon — the
    chunked / onetok RATIO is the signal and the mkchunk_smoke gate
    checks it ≥ 2x (one chunk dispatch retires a bucket of prompt
    tokens; one prefill tick retires exactly one)."""
    import jax
    import jax.numpy as jnp  # noqa: F401 — backend warmup
    from jax.sharding import Mesh

    from triton_dist_tpu.megakernel.engine import MegaKernelEngine
    from triton_dist_tpu.models.config import ModelConfig
    from triton_dist_tpu.ops.chunked_prefill import plan_chunks
    from triton_dist_tpu.serving import ServingEngine

    cfg = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                           intermediate_size=32, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2,
                           head_dim=8)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    buckets = (16,)
    kw = dict(batch=2, max_len=48, tile_w=16, t_tile=16, paged=True,
              page=16, num_pages=7)
    # Prefill-heavy: ~30 prompt tokens per request, 2 generated.
    prompts = [[(7 * i + j) % 60 + 1 for j in range(30)]
               for i in range(2)]
    n_chunks = sum(len(plan_chunks(len(p), buckets)) for p in prompts)

    out = {"megakernel_prefill_chunk_ms": None,
           "megakernel_tokens_per_s_prefill_heavy": {}}
    for name, bk in (("onetok", None), ("chunked", buckets)):
        mk = MegaKernelEngine(cfg, mesh, prefill_buckets=bk, **kw)
        s = ServingEngine(mk, prefill_buckets=bk)
        s.generate([p[:18] for p in prompts],
                   max_new_tokens=2)               # compile warmup
        t0 = time.perf_counter()
        toks = s.generate(prompts, max_new_tokens=2)
        dt = time.perf_counter() - t0
        n_tok = sum(len(p) for p in prompts) + sum(len(t) for t in toks)
        out["megakernel_tokens_per_s_prefill_heavy"][name] = round(
            n_tok / max(dt, 1e-9), 2)
        if bk:
            # Whole-run wall over the chunk count: prefill dominates
            # this workload, so this upper-bounds the per-chunk cost.
            out["megakernel_prefill_chunk_ms"] = round(
                dt * 1e3 / max(n_chunks, 1), 3)
            assert s.prefill_cache_size() <= len(bk), (
                "chunk jit cache outgrew the bucket count")
    h = out["megakernel_tokens_per_s_prefill_heavy"]
    out["megakernel_prefill_chunk_speedup"] = round(
        h["chunked"] / max(h["onetok"], 1e-9), 2)
    return out


def _interpret_serving_times() -> dict:
    """Serving throughput on the CPU mesh: the continuous-batching
    ServingEngine vs gang ("static") batching over the SAME engine and
    workload — a skewed gen-length mix, so static burns decode slots on
    finished requests while continuous recycles them. Absolute numbers
    track the XLA-on-CPU decode step, not silicon; the continuous /
    static RATIO is the scheduling win and is shape-stable."""
    import jax
    import jax.numpy as jnp  # noqa: F401 — backend warmup
    from jax.sharding import Mesh

    from triton_dist_tpu.models import Engine, ModelConfig
    from triton_dist_tpu.serving import ServingEngine

    cfg = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                           intermediate_size=32, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=4,
                           head_dim=8)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    eng = Engine(cfg, mesh, mode="xla", max_len=32, seed=0)
    prompts = [[1, 2, 3], [4, 5], [6, 7, 8], [9], [10, 11], [12]]
    gens = [2, 10, 2, 10, 2, 10]          # skewed: static wastes slots

    out = {"serving_tokens_per_s": {}, "serving_decode_dispatches": {},
           "serving_decode_cache_entries": {}}
    for policy in ("continuous", "static"):
        srv = ServingEngine(eng, num_slots=2, page=8, policy=policy)
        srv.generate([[1, 2]], max_new_tokens=2)     # compile warmup
        for k in srv.stats_counters:
            srv.stats_counters[k] = type(srv.stats_counters[k])(0)
        for p, g in zip(prompts, gens):
            srv.submit(p, max_new_tokens=g)
        srv.run()
        st = srv.stats()
        out["serving_tokens_per_s"][policy] = round(
            st.get("tokens_per_s", 0.0), 2)
        out["serving_decode_dispatches"][policy] = st[
            "decode_dispatches"]
        out["serving_decode_cache_entries"][policy] = (
            srv.decode_cache_size())

    # Chunked vs monolithic prefill on a PREFILL-HEAVY mixed-length
    # trace (every prompt a distinct length — the serving reality
    # ROADMAP Open item 1 names): monolithic prefill compiles once per
    # length, chunked once per bucket, so the wall-clock ratio here is
    # dominated by exactly the compile tax the bucketing removes.
    # Wall time INCLUDES prefill (unlike tokens_per_s above) — that is
    # the number disaggregation/chunking moves. Fresh engine per
    # variant: the jit caches must not be shared.
    rng = np.random.RandomState(0)
    trace = [[int(t) for t in rng.randint(0, 64, n)]
             for n in (3, 5, 7, 9, 11, 14, 17, 21)]

    def run_trace(buckets):
        e = Engine(cfg, mesh, mode="xla", max_len=32, seed=0)
        s = ServingEngine(e, num_slots=2, page=8,
                          prefill_buckets=buckets)
        t0 = time.perf_counter()
        s.generate(trace, max_new_tokens=4)
        dt = time.perf_counter() - t0
        return dt, s.stats()["tokens_generated"], s.prefill_cache_size()

    dt_m, toks_m, pre_m = run_trace(None)
    dt_c, toks_c, pre_c = run_trace((8,))
    out["prefill_chunked_vs_monolithic_ms"] = {
        "monolithic": round(dt_m * 1e3, 1),
        "chunked": round(dt_c * 1e3, 1)}
    out["serving_tokens_per_s_prefill_heavy"] = {
        "monolithic": round(toks_m / max(dt_m, 1e-9), 2),
        "chunked": round(toks_c / max(dt_c, 1e-9), 2)}
    out["serving_prefill_cache_entries"] = {
        "monolithic": pre_m, "chunked": pre_c}

    # Speculative decode on/off over the SAME repetitive decode-heavy
    # trace (the workload speculation exists for: greedy decode of
    # looping/templated continuations, where the n-gram self-draft
    # predicts several tokens per dispatch). Ratio = dispatches
    # amortized; absolute numbers track the CPU dispatch overhead.
    spec_trace = [[1, 2, 3, 1, 2, 3, 1, 2], [7, 8, 7, 8, 7, 8],
                  [5, 5, 5, 5], [9, 10, 11, 9, 10, 11]]
    out["serving_tokens_per_s_spec"] = {}
    out["serving_spec_accept_rate"] = None
    for name, k in (("nospec", 0), ("spec", 4)):
        e = Engine(cfg, mesh, mode="xla", max_len=96, seed=0)
        s = ServingEngine(e, num_slots=1, page=8, spec_k=k)
        # Warm with the SAME trace: prefill compiles once per distinct
        # prompt length — the timed pass measures the steady-state
        # decode loop (the surface speculation moves), not the
        # per-length compile tax the chunked-prefill key already owns.
        s.generate(spec_trace, max_new_tokens=32)
        for c in s.stats_counters:
            s.stats_counters[c] = type(s.stats_counters[c])(0)
        t0 = time.perf_counter()
        s.generate(spec_trace, max_new_tokens=32)
        dt = time.perf_counter() - t0
        st = s.stats()
        out["serving_tokens_per_s_spec"][name] = round(
            st["tokens_generated"] / max(dt, 1e-9), 2)
        if k:
            out["serving_spec_accept_rate"] = (
                None if st["spec"]["accept_rate"] is None
                else round(st["spec"]["accept_rate"], 4))
            assert s.decode_cache_size() == 1, (
                "spec verify dispatch re-specialized")

    # Telemetry: TTFT / inter-token-latency percentiles from the
    # counters-mode histograms over the same skewed trace, plus the
    # telemetry overhead — counters-mode wall clock vs telemetry="off"
    # on identical traffic (best-of-3 each; the acceptance bar is
    # < 5%, and the honest expectation is ~0: counters mode costs two
    # clock reads and a bisect per instrumented region while every
    # dispatch is an XLA call).
    def telemetry_run(mode):
        srv = ServingEngine(eng, num_slots=2, page=8, telemetry=mode)
        srv.generate([[1, 2]], max_new_tokens=2)     # compile warmup
        best = float("inf")
        for _ in range(3):
            for k in srv.stats_counters:
                srv.stats_counters[k] = type(srv.stats_counters[k])(0)
            for p, g in zip(prompts, gens):
                srv.submit(p, max_new_tokens=g)
            t0 = time.perf_counter()
            srv.run()
            best = min(best, time.perf_counter() - t0)
        return best, srv.stats()

    t_off, _ = telemetry_run("off")
    t_cnt, st_cnt = telemetry_run("counters")
    lat = st_cnt.get("latency") or {}

    def _pcts(series):
        s = lat.get(series) or {}
        return {"p50": s.get("p50"), "p99": s.get("p99")}

    out["serving_ttft_ms"] = _pcts("ttft_ms")
    out["serving_itl_ms"] = _pcts("itl_ms")
    out["telemetry_overhead_pct"] = round(
        (t_cnt / max(t_off, 1e-9) - 1.0) * 100.0, 2)

    # Quantized paged KV: HBM cost per token at each kv_dtype (from
    # the model plan) and the paged decode step's wall time bf16 vs
    # int8/fp8 through the SAME ServingEngine decode dispatch (ref
    # attention on this CPU host — dequant-on-gather; the TPU kernel
    # fuses the dequant into the page prefetch).
    out["kv_bytes_per_token"] = {}
    out["paged_decode_quant_ms"] = {}
    for kvd in ("bf16", "int8", "fp8"):
        e = Engine(cfg, mesh, mode="xla", max_len=32, seed=0)
        s = ServingEngine(e, num_slots=2, page=8, kv_dtype=kvd)
        out["kv_bytes_per_token"][kvd] = round(
            s.plan["bytes_per_token"], 2)
        s.generate([[1, 2, 3]], max_new_tokens=2)   # compile warmup
        s.submit([4, 5, 6], max_new_tokens=8)
        s.submit([7, 8], max_new_tokens=8)
        n0 = s.stats()["decode_dispatches"]
        t0 = time.perf_counter()
        s.run()
        dt = time.perf_counter() - t0
        n = s.stats()["decode_dispatches"] - n0
        out["paged_decode_quant_ms"][kvd] = round(
            dt * 1e3 / max(n, 1), 3)
    return out


def _interpret_ep_times() -> dict:
    """Decode-batch EP dispatch round-trip, ragged vs low-latency, on
    the interpret mesh — the ``detail.ep_dispatch_ms`` surface a
    CPU-only host must still fill (non-null gate in scripts/
    ep_smoke.sh). ``ragged`` times the exact-splits
    ep_dispatch/ep_combine pair; ``ll`` times the count-free
    wire-quantized ll_a2a there-and-back at the same (B·K, d) payload
    (force_kernel: the single-chip mesh must still run the full slot-
    parity kernel, not the short-circuit). Interpreter-step overhead,
    not silicon — meaningful as presence + relative shape only."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from triton_dist_tpu.ops.ep_a2a import (create_ep_context,
                                            ep_dispatch, ep_combine)
    from triton_dist_tpu.ops.low_latency import ll_a2a
    from triton_dist_tpu.parallel.mesh import MeshContext
    from triton_dist_tpu.utils.testing import spmd

    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    mctx = MeshContext.from_mesh(mesh)
    b, k, d, e = 4, 2, 32, 8
    ctx = create_ep_context(mctx, num_experts=e, topk=k, axis="tp")
    x = jax.random.normal(jax.random.PRNGKey(0), (b, d), jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, k), 0, e)
    w = jax.nn.softmax(jax.random.normal(jax.random.PRNGKey(2), (b, k)),
                       axis=-1)

    def ragged(tok, ids_, w_):
        recv, _, st = ep_dispatch(tok, ids_, ctx)
        return ep_combine(recv, st, w_, ctx)

    def ll(tok, ids_, w_):
        del ids_, w_
        payload = jnp.repeat(tok, k, axis=0)[None]      # (1, BK, d)
        out = ll_a2a(payload, ctx=mctx, axis="tp", step=0,
                     force_kernel=True)
        back = ll_a2a(out, ctx=mctx, axis="tp", step=1,
                      force_kernel=True)
        return back[0]

    specs = (P(None, None), P(None, None), P(None, None))
    steps = {
        "ragged": spmd(mesh, ragged, specs, P(None, None)),
        "ll": spmd(mesh, ll, specs, P(None, None)),
    }
    out = {}
    for name, step in steps.items():
        np.asarray(step(x, ids, w))                     # warmup
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(step(x, ids, w))
            best = min(best, time.perf_counter() - t0)
        out[name] = round(best * 1e3, 3)
    return {"ep_dispatch_ms": out,
            "ep_dispatch_shape": {"batch": b, "topk": k, "hidden": d,
                                  "experts": e}}


def _interpret_ep2d() -> dict:
    """Hierarchical 2-hop EP decode dispatch, ``ar`` vs ``ll2d``, on
    the interpret mesh — the ``detail.ep_dispatch_2d_ms`` surface a
    CPU-only host must still fill (non-null gate in
    scripts/ep2d_smoke.sh). One device plays a degenerate 1×1
    (dcn, ici) hierarchy: both hops still trace, so the trace-time put
    ledger records the real hop schedule, and the ``ep2d_dcn_puts``
    block reports the canonical 2×4 arithmetic the schedule implies —
    1 DCN slab put per dispatch where the flat ``ll`` pays 4.
    Interpreter-step overhead, not silicon — presence + relative shape
    only."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from triton_dist_tpu.layers import ep_moe
    from triton_dist_tpu.models.config import ModelConfig
    from triton_dist_tpu.ops.ep_a2a import create_ep2d_context
    from triton_dist_tpu.ops.ll_a2a_2d import record_dispatch_puts
    from triton_dist_tpu.parallel.mesh import MeshContext
    from triton_dist_tpu.utils.testing import spmd

    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1),
                ("dcn", "ici"))
    mctx = MeshContext.from_mesh(mesh)
    b, k, d, e = 4, 2, 32, 8
    cfg = ModelConfig.tiny_moe(hidden_size=d, moe_intermediate_size=16,
                               num_experts=e, num_experts_per_tok=k)
    ctx = create_ep2d_context(mctx, num_experts=e, topk=k,
                              outer_axis="dcn", inner_axis="ici")
    params = ep_moe.init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (b, d), jnp.float32)
    axis = ("dcn", "ici")
    pspecs = {name: ep_moe.param_specs(axis)[name] for name in params}

    def step_for(tr):
        return spmd(mesh,
                    lambda p, v, _tr=tr: ep_moe.fwd_decode(
                        p, v, topk=k, axis=axis, transport=_tr,
                        ep_ctx=ctx),
                    (pspecs, P(None, None)), P(None, None))

    out = {}
    for tr in ("ar", "ll2d"):
        step = step_for(tr)
        np.asarray(step(params, x))                     # warmup
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(step(params, x))
            best = min(best, time.perf_counter() - t0)
        out[tr] = round(best * 1e3, 3)

    # The put schedule, read off an actual dispatch trace (hop order
    # and per-hop put arithmetic are shape-static, so the degenerate
    # mesh records the same 2-hop schedule a real hierarchy issues).
    with record_dispatch_puts() as led:
        jax.eval_shape(step_for("ll2d"), params, x)
    puts = {"hops_traced": [ev["hop"] for ev in led],
            # canonical 2 nodes x 4 chips: (n_out-1) vs (n_out-1)*n_in
            "hierarchy": "2x4", "ll2d": 1, "flat_ll": 4}
    return {"ep_dispatch_2d_ms": out,
            "ep2d_dcn_puts": puts,
            "ep_dispatch_2d_shape": {"batch": b, "topk": k, "hidden": d,
                                     "experts": e}}


def _interpret_qblock_times() -> dict:
    """Paged Q-block attention, flash kernel vs gather ref, on the
    interpret mesh — the ``chunk_attend_ms`` / ``verify_attend_ms``
    surface a CPU-only host must still fill (non-null gate in
    scripts/qblock_smoke.sh). Shapes mirror the serving reality the
    kernel exists for: a pool sized for the CAPACITY (p_max·page) with
    slots resident far below it — the gather ref materializes every
    slot's full dense row per call, the kernel walks only the resident
    pages, so flash <= ref even at interpreter-step overhead. The
    verify shape is the K-candidate decode batch, the chunk shape one
    slot's bucketed chunk."""
    import jax
    import jax.numpy as jnp

    from triton_dist_tpu.ops.paged_flash_qblock import (
        paged_flash_qblock, paged_flash_qblock_ref)

    kvh, rep, hd, page, p_max = 4, 2, 32, 32, 16
    h = kvh * rep
    resident = 40                   # tokens actually resident per slot

    def one(b, cq):
        rng = np.random.RandomState(0)
        num_pages = b * p_max + 1
        kp = jnp.asarray(rng.randn(num_pages, kvh, page, hd)
                         .astype(np.float32))
        vp = jnp.asarray(rng.randn(num_pages, kvh, page, hd)
                         .astype(np.float32))
        tbl = jnp.asarray((1 + np.arange(b * p_max))
                          .reshape(b, p_max).astype(np.int32))
        q = jnp.asarray(rng.randn(b, cq, h, hd).astype(np.float32))
        pos = jnp.asarray((resident + np.arange(cq))[None]
                          .repeat(b, 0).astype(np.int32))
        out = {}
        for name, fn in (("flash", paged_flash_qblock),
                         ("ref", paged_flash_qblock_ref)):
            step = jax.jit(lambda *a, _f=fn: _f(*a))
            np.asarray(step(q, kp, vp, tbl, pos))      # warmup
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(step(q, kp, vp, tbl, pos))
                best = min(best, time.perf_counter() - t0)
            out[name] = round(best * 1e3, 3)
        return out

    return {
        "chunk_attend_ms": one(1, 32),      # one slot, bucket of 32
        "verify_attend_ms": one(4, 4),      # 4 slots, K=4 candidates
        "qblock_shape": {"kv_heads": kvh, "gqa": rep, "head_dim": hd,
                         "page": page, "p_max": p_max,
                         "resident_tokens": resident},
    }


def _interpret_chaos() -> dict:
    """A short seeded chaos soak through the fault-tolerant serving
    stack on the CPU mesh — the ``detail.chaos_survived_faults``
    surface (non-null gate in scripts/chaos_smoke.sh): seeded mixed
    traffic + injected dropped/wedged migrations, chunk faults, decode
    faults and a worker kill, with the invariant checker after every
    tick and token-exactness vs the fault-free oracle. A completed
    soak IS the result — any violation raises and nulls the keys."""
    import jax
    from jax.sharding import Mesh

    from triton_dist_tpu.models import Engine, ModelConfig
    from triton_dist_tpu.resilience import chaos
    from triton_dist_tpu.resilience.policy import RetryPolicy
    from triton_dist_tpu.serving import DisaggServingEngine

    cfg = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                           intermediate_size=32, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=4,
                           head_dim=8)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))

    def factory():
        eng = Engine(cfg, mesh, mode="xla", max_len=32, seed=0)
        return DisaggServingEngine(
            eng, num_slots=2, page=8, prefill_buckets=(4, 8),
            prefix_reuse=True, retry=RetryPolicy(max_attempts=2),
            worker_fail_threshold=2)

    rep = chaos.run_soak(factory, seed=11, ticks=40, n_faults=5,
                         restore_at=18)
    return {
        "chaos_survived_faults": rep.survived_faults,
        "chaos_ticks": rep.ticks,
        "chaos_requests": rep.requests,
        "chaos_retries": rep.counters["retries"],
        "chaos_failovers": rep.counters["failovers"],
        "chaos_restored_requests": rep.counters["restored_requests"],
        "chaos_invariant_checks": rep.invariant_checks,
    }


def _interpret_supervised() -> dict:
    """Process-level fault domain on the CPU mesh — the
    ``crash_recovery_ms`` / ``supervised_survived_faults`` /
    ``integrity_checks`` surface (non-null gate in
    scripts/supervise_smoke.sh): a short seeded supervised soak (a
    REAL child process SIGKILLed and stalled mid-serve, streams
    resumed token-exact from the checkpoint ring) plus the in-process
    integrity drill (seeded payload corruption at the tier /
    migration / handoff boundaries, each detected and recovered).  A
    completed run IS the result — divergence or a missed detection
    raises and nulls the keys."""
    import tempfile

    from triton_dist_tpu.resilience import chaos

    rep = chaos.run_supervised_soak(
        checkpoint_dir=tempfile.mkdtemp(prefix="tdt-sup-bench-"),
        seed=11, n_requests=3, n_faults=2,
        kinds=(("kill_child", None, None),
               ("stall_child", None, None)),
        gen_choices=(4, 6), deadline_s=300.0)
    drill = chaos.run_integrity_drill()
    rec = rep.supervisor.get("last_recovery_ms")
    return {
        "crash_recovery_ms": round(rec, 1) if rec else None,
        "supervised_survived_faults": rep.survived_faults,
        "supervised_restarts": rep.supervisor["restarts"],
        "supervised_dedup_dropped": rep.supervisor["dedup_dropped"],
        "integrity_checks": (drill["tier_checks"]
                            + drill["migration_integrity_failures"]
                            + drill["handoff_integrity_failures"]),
        "integrity_quarantined": drill["tier_quarantined"],
    }


def _interpret_tiers() -> dict:
    """Tiered KV memory hierarchy on the CPU mesh — the
    ``kv_hot_hit_rate`` / ``session_resume_ms`` / ``offloaded_pages``
    surface (non-null gate in scripts/tier_smoke.sh): a seeded
    heavy-tailed multi-turn trace over a 100k-session id space served
    through an HBM pool sized WELL below the working set, so cold
    prefixes demote into the host tier and hot reuse prefetches them
    back; plus a park/resume drill whose resume latency (requeue →
    token-exact reactivation, prefetch overlapped against decode)
    lands in the per-op histogram. Absolute times track the CPU
    dispatch, not silicon; the hit rate and the non-null presence are
    the gates."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from triton_dist_tpu.models import Engine, ModelConfig
    from triton_dist_tpu.serving import ServingEngine, heavy_tail_trace
    from triton_dist_tpu.serving.tiers import extend_session

    cfg = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                           intermediate_size=32, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=4,
                           head_dim=8)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    eng = Engine(cfg, mesh, mode="xla", max_len=32, seed=0)
    srv = ServingEngine(eng, num_slots=2, page=4, num_pages=12,
                        prefix_reuse=True, prefill_buckets=(4, 8),
                        kv_tiers={"host_pages": 512})
    events = heavy_tail_trace(28, n_sessions=100_000, vocab=64, seed=7,
                              max_total=20)
    history = {}
    t0 = time.perf_counter()
    for ev in events:
        prompt = extend_session(history, ev, max_prompt=12)
        h = srv.submit(prompt, max_new_tokens=ev["gen"])
        srv.run()
        extend_session(history, ev, reply=h.tokens)
    trace_dt = time.perf_counter() - t0
    # Park/resume drill: 3 sessions parked mid-decode and resumed —
    # the resume span (requeue -> reactivation) feeds the histogram.
    for i in range(3):
        h = srv.submit([1 + i, 2, 3], max_new_tokens=5)
        while h.status != "running":
            srv.step()
        srv.step()
        srv.park(h)
        srv.resume(h)
        srv.run()
        assert h.status == "done"
    st = srv.stats()
    resume = (st["latency"]["ops"].get("resume") or {})
    assert srv.decode_cache_size() == 1, "tiering re-specialized decode"
    return {
        "kv_hot_hit_rate": st["kv_hot_hit_rate"],
        "session_resume_ms": resume.get("mean"),
        "offloaded_pages": st["offloaded_pages"],
        "tier_detail": {
            "trace_events": len(events),
            "trace_session_space": 100_000,
            "distinct_sessions": len({e["session"] for e in events}),
            "trace_wall_ms": round(trace_dt * 1e3, 1),
            "tier_hits": st["tier_hits"],
            "tier_misses": st["tier_misses"],
            "prefetched_pages": st["prefetched_pages"],
            "demotions": st["pool"]["demotions"],
            "parks": st["parks"], "resumes": st["resumes"],
            "session_resume_p99_ms": resume.get("p99"),
            "hbm_pool_pages": 12,
        },
    }


def _interpret_fleet() -> dict:
    """Fleet-scale serving on the CPU mesh — the
    ``fleet_p99_ttft_ms`` / ``fleet_failover_resumed`` /
    ``fleet_shed_requests`` / ``router_affinity_hit_rate`` surface
    (non-null gate in scripts/fleet_smoke.sh): a seeded heavy-tailed
    multi-turn trace routed with prefix affinity across R=2 fleets, a
    mid-run reachable fleet kill whose running session fails over
    cross-fleet through the parked-tier path (token-exactness
    asserted inline), and a saturation drill that sheds one
    batch-class request. Absolute times track the CPU dispatch; the
    counters and non-null presence are the gates."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from triton_dist_tpu.models import Engine, ModelConfig
    from triton_dist_tpu.resilience import chaos
    from triton_dist_tpu.serving import (
        FleetRouter, ServingEngine, heavy_tail_trace,
    )
    from triton_dist_tpu.serving.tiers import extend_session

    cfg = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                           intermediate_size=32, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=4,
                           head_dim=8)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    eng = Engine(cfg, mesh, mode="xla", max_len=32, seed=0)

    def factory(**kw):
        args = dict(num_slots=2, page=4, num_pages=16,
                    prefix_reuse=True, kv_tiers={"host_pages": 128})
        args.update(kw)
        return ServingEngine(eng, **args)

    router = FleetRouter(lambda: factory(), fleets=2)
    events = heavy_tail_trace(24, n_sessions=40, vocab=64, seed=5,
                              zipf_a=1.2, turn_tokens=(4, 8),
                              max_total=16)
    history = {}
    t0 = time.perf_counter()
    for ev in events:
        prompt = extend_session(history, ev, max_prompt=16)
        h = router.submit(prompt, max_new_tokens=ev["gen"])
        router.run()
        extend_session(history, ev, reply=h.tokens)
    trace_dt = time.perf_counter() - t0
    # Mid-run fleet-kill drill: a running session fails over through
    # the parked-tier hop and must resume token-exact.
    prompt = [5, 5, 5, 5, 5, 5, 5, 5]
    ids = np.tile(np.asarray([prompt], np.int32), (1, 1))
    want = np.asarray(eng.serve(jnp.asarray(ids),
                                gen_len=8))[0].tolist()
    h = router.submit(prompt, max_new_tokens=8)
    for _ in range(200):
        if h.status == "running" and h.tokens:
            break
        router.step()
    victim = router._fleet_of(h)
    router.kill_fleet(victim.id, reachable=True)
    chaos.check_fleet_invariants(router, [h])
    router.run()
    assert h.status == "done" and h.tokens == want, (
        "cross-fleet failover diverged from the single-engine oracle")
    st = router.stats()
    assert all(n == 1 for n in router.decode_cache_sizes()), (
        "fleet routing re-specialized a decode dispatch")
    # Saturation shed drill (tiny queues, batch class): deterministic
    # graceful degradation so the shed counter is a real measurement.
    shed_router = FleetRouter(
        lambda: factory(num_slots=1, max_queue=1, kv_tiers=None),
        fleets=2, max_queue=0, affinity=False)
    backlog = [shed_router.submit([i + 1, 2], max_new_tokens=2)
               for i in range(2)]
    dropped = shed_router.submit([9, 9], max_new_tokens=2)
    assert dropped.status == "shed"
    shed_router.run()
    assert all(b.status == "done" for b in backlog)
    ttft = st["fleet_ttft_ms"] or {}
    return {
        "fleet_p99_ttft_ms": ttft.get("p99"),
        "fleet_failover_resumed": st["failover_resumed"],
        "fleet_shed_requests":
            shed_router.stats()["shed_requests"],
        "router_affinity_hit_rate": st["router_affinity_hit_rate"],
        "fleet_detail": {
            "fleets": 2,
            "trace_events": len(events),
            "trace_wall_ms": round(trace_dt * 1e3, 1),
            "routed": st["routed"],
            "spillovers": st["spillovers"],
            "fleet_failovers": st["fleet_failovers"],
            "failover_reprefilled": st["failover_reprefilled"],
            "kv_hot_hit_rate": st["kv_hot_hit_rate"],
            "fleet_p50_ttft_ms": ttft.get("p50"),
            "live_fleets": st["live_fleets"],
        },
    }


def _interpret_slo() -> dict:
    """Multi-tenant SLO scheduling on the CPU mesh — the
    ``slo_attainment`` / ``tenant_interactive_p99_ttft_ms`` /
    ``slo_preemptions`` surface (non-null gate in
    scripts/slo_smoke.sh): the SAME seeded mixed-tenant trace (a bulk
    batch flood plus periodic interactive arrivals with deadlines)
    served twice on a fake tick clock — once FIFO, once through the
    SLO layer with preemption armed. The measurement is the isolation
    ratio: interactive p99 TTFT must improve >= 2x under SLO while the
    bulk tenant's tokens/s degrades <= 20% (ISSUE 20's acceptance
    bar), with every stream bit-identical to ``Engine.serve`` and the
    decode jit cache at one entry. Absolute tick counts track the CPU
    dispatch; the ratio and the non-null presence are the gates."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from triton_dist_tpu.models import Engine, ModelConfig
    from triton_dist_tpu.serving import ServingEngine

    cfg = ModelConfig.tiny(vocab_size=64, hidden_size=32,
                           intermediate_size=32, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=4,
                           head_dim=8)
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    eng = Engine(cfg, mesh, mode="xla", max_len=32, seed=0)

    def run_trace(slo):
        clock = [0.0]
        srv = ServingEngine(eng, num_slots=2, page=4,
                            clock=lambda: clock[0], slo=slo)
        bulk = [srv.submit([i + 1, 2, 3], max_new_tokens=12,
                           tenant="bulk") for i in range(4)]
        chat, tick, t0 = [], 0, time.perf_counter()
        while not srv._drained() or len(chat) < 4:
            if tick % 2 == 0 and len(chat) < 4:
                # Deadline 12 ticks out: comfortably past the ~6-tick
                # service time, close enough that a chat stuck >= 2
                # ticks behind the flood enters the preemption margin.
                # The FIFO baseline gets the tenant label only — a
                # scheduler that ignores deadlines would otherwise
                # EXPIRE these requests, not serve them late.
                kw = ({"deadline": clock[0] + 12.0}
                      if slo is not None else {})
                chat.append(srv.submit([40 + len(chat), 7],
                                       max_new_tokens=4,
                                       tenant="chat", **kw))
            srv.step()
            clock[0] += 1.0
            tick += 1
            assert tick < 500, "slo bench trace failed to drain"
        wall = time.perf_counter() - t0
        for h in bulk + chat:
            n = h.request.max_new_tokens
            ids = jnp.asarray(np.tile(np.asarray(
                [list(h.request.prompt)], np.int32), (1, 1)))
            want = np.asarray(eng.serve(ids, gen_len=n))[0].tolist()
            assert h.tokens == want, (
                f"slo={slo is not None}: stream diverged from the "
                f"serve oracle for {h.request.request_id}")
        assert srv.decode_cache_size() == 1, (
            "SLO scheduling re-specialized the decode dispatch")
        st = srv.stats()
        lat = st["latency"]["per_tenant"]["chat"]["ttft_ms"]
        # Batch throughput over the full serving window — last-finish
        # would penalize the REORDERING itself (batch inherently
        # finishes later when interactive runs first), not lost work.
        return {
            "p99_ttft": lat["p99"], "ticks": tick, "wall": wall,
            "bulk_tokens_per_tick": 4 * 12 / tick, "stats": st,
        }

    fifo = run_trace(None)
    slo = run_trace({"specs": [{"name": "chat", "weight": 2.0}],
                     "preempt_margin_s": 10.0})
    isolation = fifo["p99_ttft"] / max(slo["p99_ttft"], 1e-9)
    bulk_ratio = (slo["bulk_tokens_per_tick"]
                  / max(fifo["bulk_tokens_per_tick"], 1e-9))
    st = slo["stats"]
    assert isolation >= 2.0, (
        f"interactive isolation only {isolation:.2f}x (need >= 2x)")
    assert bulk_ratio >= 0.8, (
        f"bulk throughput degraded to {bulk_ratio:.2f} (floor 0.8)")
    assert st["slo_preemptions"] >= 1
    return {
        "slo_attainment": st["slo_attainment"],
        "tenant_interactive_p99_ttft_ms": st[
            "latency"]["per_tenant"]["chat"]["ttft_ms"]["p99"],
        "slo_preemptions": st["slo_preemptions"],
        "slo_detail": {
            "interactive_isolation_x": round(isolation, 2),
            "fifo_interactive_p99_ttft_ms": fifo["p99_ttft"],
            "bulk_throughput_ratio": round(bulk_ratio, 3),
            "fifo_ticks": fifo["ticks"], "slo_ticks": slo["ticks"],
            "slo_wall_ms": round(slo["wall"] * 1e3, 1),
            "tenants": {t: {k: v[k] for k in
                            ("admitted", "released", "preempted",
                             "met", "missed")}
                        for t, v in st["slo"]["tenants"].items()},
        },
    }


def _variant_best_ms(sweep, variant, block_m=None):
    """Best swept time (ms) for one ag_gemm variant, optionally pinned
    to one block_m; None — not omitted — when nothing lowered."""
    ts = [t for t, c, _ in sweep
          if c.get("variant", "panel") == variant
          and (block_m is None or c.get("block_m") == block_m)]
    return round(min(ts) * 1e3, 3) if ts else None


def _interpret_ag_variants() -> dict:
    """Panel-vs-pipelined crossover on the interpret mesh: both
    variants at block_m {128, 256, 512} on the same sim ring and
    shape. Interpreter ratios track schedule/body-count overhead, not
    silicon overlap — but the pipelined variant runs its REAL
    scoped-VMEM streamed kernel here (no fallback exists), so the
    comparison is meaningful for gating: the streamed grid has no kk
    dimension, and a regression that re-bloats its body count or
    staging shows up as pipelined >> panel.

    Shape: m_loc=512 after the sim-4 split so block_m=512 is a real
    single-row-tile grid; K=32 with block_k=16 gives each variant two
    k-steps (the panel as grid bodies, the stream as rotating
    buffers) while every staged buffer stays <= 64 KB — the interpret
    harness starves above that.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from triton_dist_tpu.ops import ag_gemm, create_ag_gemm_context
    from triton_dist_tpu.parallel.mesh import MeshContext
    from triton_dist_tpu.utils.testing import spmd

    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    mctx = MeshContext.from_mesh(mesh)
    sim = 4
    a = jax.random.normal(jax.random.PRNGKey(4), (2048, 32), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(5), (32, 64), jnp.float32)
    want = np.asarray(a, np.float32) @ np.asarray(b, np.float32)

    crossover = {}
    best = {"panel": None, "pipelined": None}
    for bm in (128, 256, 512):
        row = {}
        for variant in ("panel", "pipelined"):
            ctx = create_ag_gemm_context(mctx, block_m=bm, block_n=64,
                                         block_k=16, variant=variant)
            step = spmd(mesh,
                        lambda x, w, _c=ctx: ag_gemm(x, w, _c,
                                                     sim_ranks=sim),
                        (P(None, None), P(None, None)), P(None, None))
            got = np.asarray(step(a, b), np.float32)  # warmup + gate
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
            t = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                np.asarray(step(a, b))
                t = min(t, time.perf_counter() - t0)
            row[f"{variant}_ms"] = round(t * 1e3, 3)
            if best[variant] is None or t * 1e3 < best[variant]:
                best[variant] = round(t * 1e3, 3)
        crossover[str(bm)] = row
    return {"ag_gemm_panel_ms": best["panel"],
            "ag_gemm_pipelined_ms": best["pipelined"],
            "ag_gemm_variant_crossover": crossover}


def _interpret_bench(reason: str) -> None:
    """CPU-only fallback: measure the overlap-schedule family on the
    interpret mesh instead of stalling toward a stale replay.

    The interpreter executes the REAL kernel schedule — ring puts,
    arrival waits, panel staging, swizzled chunk order — so the ratio
    below tracks schedule correctness and interpreter-step overhead,
    NOT hardware overlap efficiency (``detail.interpret_mode`` flags
    it; the last genuine hardware measurement rides along in detail).
    Small shapes: the interpreter is ~1000x silicon."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from triton_dist_tpu.ops import (ag_gemm, create_ag_gemm_context,
                                     create_gemm_rs_context, gemm_rs)
    from triton_dist_tpu.parallel.mesh import MeshContext
    from triton_dist_tpu.utils.testing import spmd

    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    mctx = MeshContext.from_mesh(mesh)
    sim = 4
    a = jax.random.normal(jax.random.PRNGKey(0), (256, 32), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (32, 64), jnp.float32)

    ag_ctx = create_ag_gemm_context(mctx, block_m=16, block_n=8)
    rs_ctx = create_gemm_rs_context(mctx, block_m=16, block_n=16)
    steps = {
        "ag_gemm": spmd(mesh, lambda x, w: ag_gemm(x, w, ag_ctx,
                                                   sim_ranks=sim),
                        (P(None, None), P(None, None)), P(None, None)),
        "gemm_rs": spmd(mesh, lambda x, w: gemm_rs(x, w, rs_ctx,
                                                   sim_ranks=sim),
                        (P(None, None), P(None, None)), P(None, None)),
        "compute": spmd(mesh,
                        lambda x, w: jnp.dot(
                            x, w, preferred_element_type=jnp.float32
                        ).astype(x.dtype),
                        (P(None, None), P(None, None)), P(None, None)),
    }
    want = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
    times = {}
    for name, step in steps.items():
        got = np.asarray(step(a, b), np.float32)  # warmup + correctness
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(step(a, b))
            best = min(best, time.perf_counter() - t0)
        times[name] = best

    eff = times["compute"] / max(times["ag_gemm"], 1e-9)
    try:
        mk = _interpret_megakernel_times()
    except Exception as e:  # megakernel bench must not sink the record
        mk = {"megakernel_decode_step_ms": None,
              "megakernel_error": str(e)[:200]}
    try:
        sv = _interpret_serving_times()
    except Exception as e:  # serving bench must not sink the record
        sv = {"serving_tokens_per_s": None,
              "prefill_chunked_vs_monolithic_ms": None,
              "serving_tokens_per_s_prefill_heavy": None,
              "serving_tokens_per_s_spec": None,
              "serving_spec_accept_rate": None,
              "kv_bytes_per_token": None,
              "paged_decode_quant_ms": None,
              "serving_ttft_ms": None,
              "serving_itl_ms": None,
              "telemetry_overhead_pct": None,
              "serving_error": str(e)[:200]}
    try:
        ep = _interpret_ep_times()
    except Exception as e:  # ep bench must not sink the record
        ep = {"ep_dispatch_ms": None, "ep_error": str(e)[:200]}
    try:
        e2 = _interpret_ep2d()
    except Exception as e:  # ep2d bench must not sink the record
        # Nulled, NOT omitted: the ep2d_smoke gate greps these keys.
        e2 = {"ep_dispatch_2d_ms": None, "ep2d_dcn_puts": None,
              "ep2d_error": str(e)[:200]}
    try:
        qb = _interpret_qblock_times()
    except Exception as e:  # qblock bench must not sink the record
        # Nulled, NOT omitted: a consumer greps the keys either way.
        qb = {"chunk_attend_ms": None, "verify_attend_ms": None,
              "qblock_error": str(e)[:200]}
    try:
        ch = _interpret_chaos()
    except Exception as e:  # chaos soak must not sink the record
        ch = {"chaos_survived_faults": None,
              "chaos_error": str(e)[:300]}
    try:
        sp = _interpret_supervised()
    except Exception as e:  # supervised soak must not sink the record
        # Nulled, NOT omitted: the supervise_smoke gate greps these.
        sp = {"crash_recovery_ms": None,
              "supervised_survived_faults": None,
              "integrity_checks": None,
              "supervise_error": str(e)[:300]}
    try:
        ti = _interpret_tiers()
    except Exception as e:  # tier bench must not sink the record
        # Nulled, NOT omitted: the tier_smoke gate greps these keys.
        ti = {"kv_hot_hit_rate": None, "session_resume_ms": None,
              "offloaded_pages": None, "tiers_error": str(e)[:300]}
    try:
        fl = _interpret_fleet()
    except Exception as e:  # fleet bench must not sink the record
        # Nulled, NOT omitted: the fleet_smoke gate greps these keys.
        fl = {"fleet_p99_ttft_ms": None,
              "fleet_failover_resumed": None,
              "fleet_shed_requests": None,
              "router_affinity_hit_rate": None,
              "fleet_error": str(e)[:300]}
    try:
        so = _interpret_slo()
    except Exception as e:  # slo bench must not sink the record
        # Nulled, NOT omitted: the slo_smoke gate greps these keys.
        so = {"slo_attainment": None,
              "tenant_interactive_p99_ttft_ms": None,
              "slo_preemptions": None,
              "slo_error": str(e)[:300]}
    try:
        mp = _interpret_mega_parity()
    except Exception as e:  # mk parity bench must not sink the record
        # Nulled, NOT omitted: the mega_parity_smoke gate greps these.
        mp = {"megakernel_decode_quant_ms": None,
              "megakernel_tokens_per_s_spec": None,
              "megakernel_spec_accept_rate": None,
              "mega_error": str(e)[:300]}
    try:
        mc = _interpret_mega_chunked()
    except Exception as e:  # mk chunked bench must not sink the record
        # Nulled, NOT omitted: the mkchunk_smoke gate greps these.
        mc = {"megakernel_prefill_chunk_ms": None,
              "megakernel_tokens_per_s_prefill_heavy": None,
              "megakernel_prefill_chunk_speedup": None,
              "mega_error": str(e)[:300]}
    try:
        av = _interpret_ag_variants()
    except Exception as e:  # variant sweep must not sink the record
        # Nulled, NOT omitted: the aggemm_smoke gate greps these keys.
        av = {"ag_gemm_panel_ms": None, "ag_gemm_pipelined_ms": None,
              "ag_gemm_variant_crossover": None,
              "ag_variant_error": str(e)[:300]}
    last, src = _load_last_result()
    out = {
        "metric": "ag_gemm_overlap_efficiency_interpret",
        "value": round(float(eff), 4),
        "unit": "ratio_vs_compute_only_gemm_interpret",
        "vs_baseline": None,   # interpreter ratios are not comparable
        "detail": {
            "interpret_mode": True,
            "backend_unavailable": True,
            "probe_verdict": reason,
            "probe_attempts": _PROBE_ATTEMPTS,
            "measured_at_unix": int(time.time()),
            "sim_ranks": sim,
            "ag_gemm_ms": round(times["ag_gemm"] * 1e3, 3),
            "gemm_rs_ms": round(times["gemm_rs"] * 1e3, 3),
            "gemm_rs_efficiency": round(
                float(times["compute"] / max(times["gemm_rs"], 1e-9)), 4),
            "compute_only_ms": round(times["compute"] * 1e3, 3),
            "shape_m_k_n": [256, 32, 64],
            **mk,
            **sv,
            **ep,
            **e2,
            **qb,
            **ch,
            **sp,
            **ti,
            **fl,
            **so,
            **mp,
            **mc,
            **av,
            # Hardware partials from an earlier run that died mid-sweep
            # (kept: this interpret record is no substitute for them).
            "partial_sweeps": _load_partials(),
            "stale_source": src,
            "stale_value": (last or {}).get("value"),
            "stale_vs_baseline": (last or {}).get("vs_baseline"),
        },
    }
    print(json.dumps(_stamp_stale_repeat(out)))


def _emit_unavailable(error: str, attempts) -> None:
    """Backend never came up: emit a JSON line that still carries the
    last known measurement — but ONLY under detail (ADVICE r3: a stale
    number under the live top-level keys reads as a fresh run to a
    consumer that never looks inside detail)."""
    last, src = _load_last_result()
    out = {
        "metric": (last or {}).get(
            "metric", "ag_gemm_overlap_efficiency_selfsim_ring"),
        "value": None,
        "unit": "ratio_vs_compute_only_gemm",
        "vs_baseline": None,
        "detail": {
            "backend_unavailable": True,
            "stale": True,
            "probe_attempts": _PROBE_ATTEMPTS,
            "stale_source": src,
            "stale_value": (last or {}).get("value"),
            "stale_vs_baseline": (last or {}).get("vs_baseline"),
            "init_attempts": attempts,
            "init_error": error,
            # Salvaged mid-sweep measurements from a prior run that
            # died before printing a record — real data, not a replay.
            "partial_sweeps": _load_partials(),
            "last_detail": (last or {}).get("detail"),
        },
    }
    print(json.dumps(_stamp_stale_repeat(out)))


# Record fields that legitimately differ between two runs that
# measured nothing new (timestamps, probe bookkeeping, crash salvage).
# Everything else identical across rounds means the record REPLAYS a
# prior round's values rather than reporting a fresh measurement.
_STALE_VOLATILE_KEYS = (
    "measured_at_unix", "probe_attempts", "init_attempts", "init_error",
    "probe_verdict", "partial_sweeps", "battery", "stale_repeat_of",
)


def _stamp_stale_repeat(out: dict) -> dict:
    """Stamp ``detail.stale_repeat_of`` when this record's measured
    values are identical to a committed prior round's (the BENCH_r02–
    r05 failure shape: a failed sweep replayed r01 byte-for-byte and
    the perf trajectory silently flatlined). Volatile bookkeeping
    fields are ignored for the comparison; genuine measurements carry
    fresh timings in detail, so two independent runs never compare
    equal. Stamps the EARLIEST matching round — a chain of replays all
    points at the one real measurement. Never raises (guarding the
    record must not sink it)."""
    def norm(rec):
        try:
            rec = json.loads(json.dumps(rec))          # deep copy
        except (TypeError, ValueError):
            return None
        det = rec.get("detail")
        if isinstance(det, dict):
            for k in _STALE_VOLATILE_KEYS:
                det.pop(k, None)
            last = det.get("last_detail")
            if isinstance(last, dict):
                for k in _STALE_VOLATILE_KEYS:
                    last.pop(k, None)
        return json.dumps(rec, sort_keys=True)
    try:
        mine = norm(out)
        if mine is None:
            return out
        here = os.path.dirname(os.path.abspath(__file__))
        for p in sorted(glob.glob(os.path.join(here, "BENCH_r*.json"))):
            try:
                with open(p) as f:
                    rec = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            parsed = rec.get("parsed") if isinstance(rec, dict) else None
            if isinstance(parsed, dict) and norm(parsed) == mine:
                out.setdefault("detail", {})["stale_repeat_of"] = (
                    os.path.basename(p))
                break
    except Exception:
        pass
    return out


def main():
    budget = float(os.environ.get("BENCH_INIT_BUDGET_S", "900"))
    backoff = float(os.environ.get("BENCH_INIT_BACKOFF_S", "30"))
    err = _probe_backend(budget, backoff)
    if err is not None:
        # No TPU: measure the overlap schedules on the interpret mesh
        # (BENCH_INTERPRET=0 restores the bare stale-replay record).
        if os.environ.get("BENCH_INTERPRET", "1") != "0":
            try:
                _interpret_bench(err)
                return
            except Exception as e:
                err = f"{err}; interpret bench failed: {str(e)[:200]}"
        _emit_unavailable(err, f"{budget:.0f}s budget")
        return

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from triton_dist_tpu.ops import ag_gemm, create_ag_gemm_context
    from triton_dist_tpu.parallel.mesh import MeshContext

    devices = [d for d in jax.devices()]
    n = len(devices)
    m_full, k_dim, n_dim = 2048, 4096, 4096
    dtype = jnp.bfloat16

    mesh = Mesh(np.array(devices), ("tp",))
    mctx = MeshContext.from_mesh(mesh)

    a = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (m_full, k_dim), dtype),
        NamedSharding(mesh, P("tp", None)))
    b = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (k_dim, n_dim), dtype),
        NamedSharding(mesh, P(None, "tp")))

    # Single chip: self-simulated ring (full multi-chip schedule with
    # self-targeted puts). Multi chip: the real overlapped collective.
    sim = SIM_RANKS if n == 1 else 0

    def make_fused_step(cfg, sim_ranks=sim):
        ctx = create_ag_gemm_context(mctx, **cfg)

        def fused_step(x, w):
            return jax.shard_map(
                lambda xs, ws: ag_gemm(
                    xs, ws, ctx, sim_ranks=sim_ranks,
                    force_kernel=(n == 1 and not sim_ranks)),
                mesh=mesh, in_specs=(P("tp", None), P(None, "tp")),
                out_specs=P(None, "tp"), check_vma=False)(x, w)
        return fused_step

    # Compute-only oracle: GEMM on already-gathered A (what overlap is
    # measured against in the reference charts, README.md:193).
    a_full = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (m_full, k_dim), dtype),
        NamedSharding(mesh, P(None, None)))

    def compute_step(x, w):
        return jax.shard_map(
            lambda xs, ws: jnp.dot(xs, ws, preferred_element_type=jnp.float32
                                   ).astype(dtype),
            mesh=mesh, in_specs=(P(None, None), P(None, "tp")),
            out_specs=P(None, "tp"), check_vma=False)(x, w)

    # Sweep block configs (tune-cache winner first), then re-time the
    # winner at full repeats. A single hardcoded config made round 1's
    # number a coin flip against tunnel noise.
    from triton_dist_tpu import tune

    tune_key = tune.make_key("ag_gemm_bench", m=m_full, k=k_dim, n=n_dim,
                             dtype=str(dtype.dtype), world=n)
    cached = tune.load_autotune_data(tune_key)
    configs = list(AG_GEMM_CONFIGS)
    if cached is not None and cached not in configs:
        configs.append(cached)  # extra candidate from a previous run

    def _sweep(name, cfgs, make_step, *args):
        """Time each config briefly; return sorted [(t, cfg, step)].
        Configs that fail to lower (e.g. VMEM overflow) are skipped —
        the autotuner's policy."""
        results, errs = [], []
        for cfg in cfgs:
            step = make_step(cfg)
            try:
                t = max(_timed_chain(step, *args, repeats=SWEEP_REPEATS),
                        1e-9)
            except Exception as e:
                errs.append(f"{cfg}: {type(e).__name__}: {str(e)[:200]}")
                continue
            # Persist AS MEASURED: a later config hanging the process
            # must not erase this one's number.
            _note_partial(name, cfg, t)
            results.append((t, cfg, step))
        assert results, f"no {name} config compiled:\n" + "\n".join(errs)
        results.sort(key=lambda e: e[0])
        return results

    def _sweep_with_sim_fallback(name, cfgs, make_step, *operands,
                                 sim_on):
        """One fallback policy for every sim-capable sweep: if EVERY
        sim config fails (the self-sim ring has only ever lowered in
        interpret mode), re-sweep rankless rather than zeroing the
        round, and RECORD WHY — a genuine Mosaic rejection stays
        distinguishable from a transient outage in the round record.
        Returns (sweep, sim_used, reason)."""
        try:
            return _sweep(name, cfgs, make_step, *operands), sim_on, None
        except Exception as e:
            # Any sim-mode failure demotes to the rankless proxy — not
            # only the sweep's final AssertionError but also failures
            # escaping step CONSTRUCTION outside the per-config loop
            # (ADVICE r4). Non-sim failures still propagate.
            if not sim_on:
                raise
            return (_sweep(name, cfgs, lambda c: make_step(c, 0),
                           *operands),
                    0, f"{name}: {type(e).__name__}: {str(e)[:600]}")

    sweep, sim, sim_fallback_reason = _sweep_with_sim_fallback(
        "ag_gemm", configs, make_fused_step, a, b, sim_on=sim)
    _, best_cfg, fused_step = sweep[0]

    # Correctness gate before persisting or timing: a fast wrong kernel
    # is worthless (and must not poison the tune cache).
    # jit the gate: the eager path compiles separately (and near VMEM
    # limits can fail where the measured jitted path does not).
    got = np.asarray(jax.jit(fused_step)(a, b), np.float32)
    want = np.asarray(compute_step(a_full, b), np.float32)
    np.testing.assert_allclose(got, want, rtol=3e-2, atol=3e-1)
    tune.store_autotune_data(tune_key, best_cfg, seconds=sweep[0][0])

    # Secondary: GEMM+RS efficiency on the transposed problem — swept
    # over configs like ag_gemm above.
    from triton_dist_tpu.ops import gemm_rs, create_gemm_rs_context
    a_rs = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(2), (m_full, k_dim), dtype),
        NamedSharding(mesh, P(None, "tp")))
    b_rs = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(3), (k_dim, n_dim), dtype),
        NamedSharding(mesh, P("tp", None)))

    def make_rs_step(cfg, sim_ranks=None):
        ctx = create_gemm_rs_context(mctx, **cfg)

        def rs_step(x, w):
            s = sim if sim_ranks is None else sim_ranks
            return jax.shard_map(
                lambda xs, ws: gemm_rs(xs, ws, ctx, sim_ranks=s,
                                       force_kernel=(n == 1 and not s)),
                mesh=mesh, in_specs=(P(None, "tp"), P("tp", None)),
                out_specs=P("tp", None), check_vma=False)(x, w)
        return rs_step

    rs_key = tune.make_key("gemm_rs_bench", m=m_full, k=k_dim, n=n_dim,
                           dtype=str(dtype.dtype), world=n)
    rs_cached = tune.load_autotune_data(rs_key)
    rs_configs = list(GEMM_RS_CONFIGS)
    if rs_cached is not None and rs_cached not in rs_configs:
        rs_configs.append(rs_cached)
    rs_sweep, rs_sim_used, rs_reason = _sweep_with_sim_fallback(
        "gemm_rs", rs_configs, make_rs_step, a_rs, b_rs, sim_on=sim)
    if rs_reason is not None:
        # Only reachable when the ag sweep kept sim (else sim_on=0
        # re-raises), so the two reasons never coexist.
        sim_fallback_reason = rs_reason
    rs_best_cfg, rs_fused = rs_sweep[0][1], rs_sweep[0][2]
    got_rs = np.asarray(jax.jit(rs_fused)(a_rs, b_rs), np.float32)
    want_rs = (np.asarray(a_rs, np.float32)
               @ np.asarray(b_rs, np.float32))
    np.testing.assert_allclose(got_rs, want_rs, rtol=3e-2, atol=3e-1)
    tune.store_autotune_data(rs_key, rs_best_cfg,
                             seconds=rs_sweep[0][0])

    # Tertiary: SP ring-attention kernel efficiency vs XLA's own dense
    # attention (the measurement the round-1 verdict flagged as missing
    # for the SP/CP family). Single-chip only: at n > 1 the fused op
    # solves a sequence-sharded n*S problem the dense chain doesn't —
    # the ratio would compare different problems (a proper multi-chip
    # attention benchmark needs sharded inputs + a global oracle).
    group = {
        "compute": (compute_step, a_full, b),
        "fused": (fused_step, a, b),
        "rs": (rs_fused, a_rs, b_rs),
    }
    if sim:
        # Continuity with rounds 1-3: the rankless pipeline number the
        # old headline reported (no ring; upper bound on the sim one).
        group["fused_rankless"] = (make_fused_step(best_cfg, 0), a, b)
    if n == 1:
        from triton_dist_tpu.ops import sp_ag_attention_fused
        from triton_dist_tpu.ops.sp_ag_attention import _masked_attn

        s_len, h_n, kvh_n, hd_n = 2048, 16, 8, 128
        s_last = s_len // SIM_RANKS
        qa = jax.device_put(
            jax.random.normal(jax.random.PRNGKey(4), (s_len, h_n, hd_n),
                              dtype) * 0.3,
            NamedSharding(mesh, P(None, None, None)))
        kv_a = tuple(
            jax.device_put(
                jax.random.normal(jax.random.PRNGKey(5 + i),
                                  (s_len, kvh_n, hd_n), dtype) * 0.3,
                NamedSharding(mesh, P(None, None, None)))
            for i in range(2))

        # Self-sim ring (only while the headline also measures sim —
        # a demoted `sim` keeps the whole record on one footing): play
        # the last of SIM_RANKS ranks, all chunk arrivals riding real
        # self-put DMAs. Oracle computes the SAME slice (last-rank
        # queries over the full KV), so the ratio compares identical
        # work, overlap machinery included. With sim demoted, fall back
        # to the rankless kernel vs the full dense oracle (rounds 1-3).
        if sim:
            def attn_fused(q_, kv_):
                return jax.shard_map(
                    lambda qq, kk, vv: sp_ag_attention_fused(
                        qq, kk, vv, ctx=mctx, axis="tp",
                        sim_ranks=SIM_RANKS),
                    mesh=mesh, in_specs=(P(None, None, None),) * 3,
                    out_specs=P(None, None, None),
                    check_vma=False)(q_, *kv_)

            def attn_xla(q_, kv_):
                return _masked_attn(q_[-s_last:], kv_[0], kv_[1],
                                    s_len - s_last).astype(q_.dtype)
        else:
            def attn_fused(q_, kv_):
                return jax.shard_map(
                    lambda qq, kk, vv: sp_ag_attention_fused(
                        qq, kk, vv, ctx=mctx, axis="tp",
                        force_kernel=True),
                    mesh=mesh, in_specs=(P(None, None, None),) * 3,
                    out_specs=P(None, None, None),
                    check_vma=False)(q_, *kv_)

            def attn_xla(q_, kv_):
                return _masked_attn(q_, kv_[0], kv_[1], 0
                                    ).astype(q_.dtype)

        # Correctness gate before timing (same policy as ag_gemm above:
        # a fast wrong kernel is worthless). Sim lowering failures are
        # recorded and the attn metric skipped, not fatal.
        try:
            np.testing.assert_allclose(
                np.asarray(attn_fused(qa, kv_a), np.float32),
                np.asarray(attn_xla(qa, kv_a), np.float32),
                rtol=3e-2, atol=3e-2)
            group["attn_fused"] = (attn_fused, qa, kv_a)
            group["attn_xla"] = (attn_xla, qa, kv_a)
        except AssertionError:
            raise    # numerics wrong: must surface, not skip
        except Exception as e:
            if sim_fallback_reason is None:
                sim_fallback_reason = f"sp_attn: {str(e)[:600]}"

    # Final numbers: every chain interleaved in ONE measurement group —
    # numerator and denominator see the same tunnel/chip conditions.
    times = _timed_chain_group(group)
    t_compute = max(times["compute"], 1e-9)
    t_fused = max(times["fused"], 1e-9)
    t_rs = max(times["rs"], 1e-9)
    t_attn_fused = max(times.get("attn_fused", 0.0), 1e-9)
    t_attn_xla = times.get("attn_xla")

    eff = t_compute / t_fused
    flops = 2 * m_full * k_dim * n_dim / max(n, 1)
    t_rankless = times.get("fused_rankless")
    result = {
        "metric": ("ag_gemm_overlap_efficiency" if n > 1 else
                   "ag_gemm_overlap_efficiency_selfsim_ring" if sim else
                   "ag_gemm_kernel_efficiency_single_chip"),
        "value": round(float(eff), 4),
        "unit": "ratio_vs_compute_only_gemm",
        "vs_baseline": round(float(eff) / 0.90, 4),
        "detail": {
            # Wall-clock stamp: a stale replay of this record (backend
            # down at round end) stays attributable to WHEN it was
            # actually measured — a mid-round measurement is fresh
            # evidence, not round-1 leftovers.
            "measured_at_unix": int(time.time()),
            "probe_attempts": _PROBE_ATTEMPTS,
            "devices": n,
            "sim_ranks": (SIM_RANKS if sim else None),
            "gemm_rs_sim": bool(rs_sim_used),
            "sim_fallback_reason": sim_fallback_reason,
            "rankless_kernel_efficiency": (
                round(float(t_compute / t_rankless), 4)
                if t_rankless else None),
            "t_fused_ms": round(t_fused * 1e3, 3),
            "t_compute_only_ms": round(t_compute * 1e3, 3),
            "fused_tflops_per_chip": round(flops / t_fused / 1e12, 2),
            "gemm_rs_ms": round(t_rs * 1e3, 3),
            "gemm_rs_efficiency": round(float(t_compute / t_rs), 4),
            "gemm_rs_best_config": rs_best_cfg,
            "sp_attn_fused_ms": (round(t_attn_fused * 1e3, 3)
                                 if t_attn_xla else None),
            "sp_attn_xla_ms": (round(t_attn_xla * 1e3, 3)
                               if t_attn_xla else None),
            "sp_attn_kernel_efficiency": (
                round(float(t_attn_xla / t_attn_fused), 4)
                if t_attn_xla else None),
            "shape_m_k_n": [m_full, k_dim, n_dim],
            "best_config": best_cfg,
            # Per-variant bests + the block_m crossover table (nulled,
            # NOT omitted, when a variant's configs all failed to
            # lower: the aggemm_smoke gate greps these keys either
            # way).
            "ag_gemm_panel_ms": _variant_best_ms(sweep, "panel"),
            "ag_gemm_pipelined_ms": _variant_best_ms(sweep, "pipelined"),
            "ag_gemm_variant_crossover": {
                str(bm): {
                    "panel_ms": _variant_best_ms(sweep, "panel", bm),
                    "pipelined_ms": _variant_best_ms(sweep, "pipelined",
                                                     bm)}
                for bm in (128, 256, 512)},
            "swept_ms": {
                (f"{c.get('variant', 'panel')}:"
                 f"{c['block_m']}x{c['block_n']}x{c['block_k']}"):
                round(t * 1e3, 3) for t, c, _ in sweep},
        },
    }

    # Persist the headline BEFORE the battery: even if the battery
    # hangs and the process is killed, the measurement survives for the
    # stale-fallback path.
    def _persist(res):
        try:
            with open(_last_result_path(), "w") as f:
                json.dump(res, f)
        except OSError:
            pass

    _persist(result)

    # Fold the hardware-battery pass rate into the headline record
    # (VERDICT r2 #1c: the battery's pass rate was never recorded in any
    # BENCH_r*.json). The battery runs in a SUBPROCESS with a hard kill
    # timeout — a hung Mosaic compile or device fetch inside one entry
    # cannot eat the round (the in-process deadline only bounds the
    # gaps *between* entries). Set BENCH_BATTERY_BUDGET_S=0 to skip.
    budget = float(os.environ.get("BENCH_BATTERY_BUDGET_S", "1500"))
    if budget > 0:
        result["detail"]["battery"] = _battery_subprocess(budget)
        dp = result["detail"]["battery"].pop("decode_perf", None)
        if dp:
            result["detail"]["decode_perf"] = dp
        _persist(result)
    # The sweeps completed and the record carries their timings — the
    # crash-salvage partials are superseded.
    _clear_partials()
    print(json.dumps(_stamp_stale_repeat(result)))


def _battery_subprocess(budget_s: float) -> dict:
    """Run ``bench.py --all`` in a child with a hard timeout; summarize
    its per-entry JSON lines."""
    here = os.path.abspath(__file__)
    env = dict(os.environ, BENCH_BATTERY_DEADLINE=str(budget_s - 60))
    try:
        r = subprocess.run([sys.executable, here, "--all"],
                           capture_output=True, text=True,
                           timeout=budget_s, env=env)
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"")
        out = out.decode() if isinstance(out, bytes) else out
        recs = _parse_battery_lines(out)
        recs["error"] = f"killed at {budget_s:.0f}s hard timeout"
        return recs
    recs = _parse_battery_lines(r.stdout)
    if r.returncode != 0:
        recs["error"] = (r.stderr.strip().splitlines() or ["rc!=0"]
                         )[-1][:200]
    return recs


def _parse_battery_lines(stdout: str) -> dict:
    ran, dropped, failed, decode_perf = 0, 0, [], None
    for line in (stdout or "").splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "op" not in rec:
            continue
        if rec.get("skipped"):
            dropped += 1
            continue
        ran += 1
        if not rec.get("ok"):
            failed.append(rec["op"])
        if rec["op"] == "engine_decode_throughput" and rec.get("ok"):
            decode_perf = {k: v for k, v in rec.items()
                           if k not in ("op", "ok", "wall_s")}
    out = {"pass_rate": round((ran - len(failed)) / max(ran, 1), 4),
           "passed": ran - len(failed), "ran": ran,
           "skipped": dropped, "failed_ops": failed}
    if decode_perf:
        out["decode_perf"] = decode_perf
    return out


def battery(quiet=False, deadline=None):
    """``bench.py --all``: execute EVERY fused op family once on the
    real chip at production-ish shapes (round-1 gap: only
    ag_gemm/gemm_rs had ever lowered on hardware — Mosaic-only failures
    in the others were invisible). Single chip, so collectives run
    rankless via force_kernel: the full Mosaic lowering (VMEM budgets,
    semaphore tables, HBM-workspace rules) executes; only the ICI wire
    is absent. Prints one JSON line per entry + a summary line."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from triton_dist_tpu.parallel.mesh import MeshContext
    import triton_dist_tpu.ops as ops

    if deadline is None and os.environ.get("BENCH_BATTERY_DEADLINE"):
        deadline = (time.perf_counter()
                    + float(os.environ["BENCH_BATTERY_DEADLINE"]))

    devices = jax.devices()
    mesh = Mesh(np.array(devices[:1]), ("tp",))
    mctx = MeshContext.from_mesh(mesh)
    dt = jnp.bfloat16

    def sm(fn, in_specs, out_specs=P(None, None)):
        return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                     out_specs=out_specs,
                                     check_vma=False))

    k0 = jax.random.PRNGKey(0)
    b4k = jax.random.normal(jax.random.PRNGKey(1), (4096, 4096), dt)
    m1k = jax.random.normal(jax.random.PRNGKey(2), (1024, 4096), dt)

    def run_gemm_ar():
        """Correctness of both exchange schemes + the decode-shape perf
        comparison the VERDICT asked for: fused gemm_ar vs the XLA dot
        (the n=1 psum oracle) at M=128 (reference
        low_latency_gemm_allreduce_op's regime, gemm_allreduce.py:669).
        Timed with the SELF-SIMULATED exchange (sim_ranks=8): the full
        push + per-slot reduce schedule runs, peers = self."""
        small = jax.random.normal(k0, (128, 4096), dt)
        want = np.asarray(small, np.float32) @ np.asarray(b4k, np.float32)
        steps = {}
        for variant in ("ll", "one_shot"):
            ctx = ops.create_gemm_ar_context(
                mctx, block_n=512, block_k=1024, variant=variant)
            f = sm(lambda x, w, c=ctx: ops.gemm_ar(x, w, c,
                                                   sim_ranks=8),
                   (P(None, None), P(None, None)))
            out = np.asarray(f(small, b4k), np.float32)
            np.testing.assert_allclose(out, want, rtol=3e-2, atol=3.0)
            steps[variant] = f

        def xla_step(x, w):
            return jnp.dot(x, w, preferred_element_type=jnp.float32
                           ).astype(dt)

        times = _timed_chain_group(
            {"ll": (steps["ll"], small, b4k),
             "one_shot": (steps["one_shot"], small, b4k),
             "xla_dot": (jax.jit(xla_step), small, b4k)},
            repeats=3, hi=72)
        return {"gemm_ar_ll_ms": round(times["ll"] * 1e3, 4),
                "gemm_ar_one_shot_ms": round(times["one_shot"] * 1e3, 4),
                "xla_dot_ms": round(times["xla_dot"] * 1e3, 4),
                "ll_vs_oracle": round(times["xla_dot"]
                                      / max(times["ll"], 1e-9), 4)}

    def run_allreduce(method):
        def go():
            f = sm(lambda x: ops.all_reduce(x, ctx=mctx, axis="tp",
                                            method=method,
                                            force_kernel=True),
                   (P(None, None),))
            out = np.asarray(f(m1k), np.float32)
            np.testing.assert_allclose(out, np.asarray(m1k, np.float32),
                                       rtol=1e-2, atol=1e-2)
        return go

    def run_allgather(mode):
        def go():
            f = sm(lambda x: ops.all_gather(x, ctx=mctx, axis="tp",
                                            mode=mode,
                                            force_kernel=True),
                   (P(None, None),))
            out = np.asarray(f(m1k), np.float32)
            np.testing.assert_allclose(out, np.asarray(m1k, np.float32))
        return go

    def run_a2a():
        x = jax.random.normal(k0, (1, 1024, 4096), dt)
        f = sm(lambda v: ops.all_to_all(v, ctx=mctx, axis="tp",
                                        force_kernel=True),
               (P(None, None, None),), P(None, None, None))
        out = np.asarray(f(x), np.float32)
        np.testing.assert_allclose(out, np.asarray(x, np.float32))

    def run_fast_allgather():
        # push_2d exercises the factored-grid _push_nd_kernel (push_1d
        # delegates to the full-mesh AG already covered above).
        x = jax.random.normal(k0, (128, 4096), dt)  # decode-shape msg
        f = sm(lambda v: ops.fast_allgather(v, ctx=mctx, axis="tp",
                                            mode="push_2d",
                                            force_kernel=True),
               (P(None, None),))
        out = np.asarray(f(x), np.float32)
        np.testing.assert_allclose(out, np.asarray(x, np.float32))

    def run_ll_a2a():
        # Decode-shape message (the op's contract: whole chunks stage
        # in VMEM; big payloads belong on all_to_all).
        x = jax.random.normal(k0, (1, 128, 4096), dt)
        f = sm(lambda v: ops.ll_a2a(v, ctx=mctx, axis="tp",
                                    force_kernel=True),
               (P(None, None, None),), P(None, None, None))
        out = np.asarray(f(x), np.float32)
        np.testing.assert_allclose(out, np.asarray(x, np.float32),
                                   rtol=0.05, atol=0.05)

    def run_ll_a2a_steps():
        """Decode-loop amortization: S=8 a2a steps fused into ONE
        kernel invocation (one entry barrier + launch, slot-parity
        wire buffers, credit flow control) vs 8 chained single-step
        calls in one jit. The per-step delta is the per-invocation
        overhead the persistent form eliminates."""
        from triton_dist_tpu.ops import ll_a2a, ll_a2a_steps

        S, c, d = 8, 128, 4096
        xs = jax.random.normal(k0, (S, 1, c, d), dt)

        multi = sm(lambda v: ll_a2a_steps(v, ctx=mctx, axis="tp",
                                          force_kernel=True),
                   (P(None, None, None, None),),
                   P(None, None, None, None))

        def chained(v):
            outs = []
            for s in range(S):
                outs.append(ll_a2a(v[s], ctx=mctx, axis="tp", step=s,
                                   force_kernel=True))
            return jnp.stack(outs)

        single = sm(chained, (P(None, None, None, None),),
                    P(None, None, None, None))
        got = np.asarray(multi(xs), np.float32)
        want = np.asarray(single(xs), np.float32)
        np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)

        times = _timed_chain_group(
            {"fused_steps": (lambda a, b_: multi(a), xs, xs),
             "chained": (lambda a, b_: single(a), xs, xs)},
            repeats=3, hi=24, lo=4)
        return {"steps_fused_ms_per_step": round(
                    times["fused_steps"] * 1e3 / S, 4),
                "steps_chained_ms_per_step": round(
                    times["chained"] * 1e3 / S, 4),
                "per_step_overhead_saved_ms": round(
                    (times["chained"] - times["fused_steps"]) * 1e3 / S,
                    4)}

    def run_moe_rs():
        y = jax.random.normal(k0, (2048, 8, 2048), dt)
        w = jax.nn.softmax(
            jax.random.normal(jax.random.PRNGKey(3), (2048, 8)), -1)
        f = sm(lambda yy, ww: ops.moe_reduce_rs(yy, ww, ctx=mctx,
                                                axis="tp", block_m=256,
                                                force_kernel=True),
               (P(None, None, None), P(None, None)))
        out = np.asarray(f(y, w), np.float32)
        want = np.einsum("tkd,tk->td", np.asarray(y, np.float32),
                         np.asarray(w, np.float32))
        np.testing.assert_allclose(out, want, rtol=3e-2, atol=3e-1)

    def run_ep_fused():
        ctx = ops.create_ep_fused_context(
            mctx, num_experts=4, topk=2, capacity_per_expert=512,
            axis="tp", block_f=512, block_d=512)
        tok = jax.random.normal(k0, (256, 1024), dt)
        ids = jax.random.randint(jax.random.PRNGKey(4), (256, 2), 0, 4)
        w = jax.nn.softmax(
            jax.random.normal(jax.random.PRNGKey(5), (256, 2)), -1
        ).astype(dt)
        kg, ku, kd = jax.random.split(jax.random.PRNGKey(6), 3)
        wg = jax.random.normal(kg, (4, 1024, 1024), dt) * 0.03
        wu = jax.random.normal(ku, (4, 1024, 1024), dt) * 0.03
        wd = jax.random.normal(kd, (4, 1024, 1024), dt) * 0.03
        f = sm(lambda *args: ops.ep_moe_fused(*args, ctx)[0],
               (P(None, None),) * 3 + (P(None, None, None),) * 3)
        out = f(tok, ids, w, wg, wu, wd)
        assert np.isfinite(np.asarray(out, np.float32)).all()

    def run_grouped(op):
        """Shared harness for the grouped-GEMM family: sorted-layout
        prep, the op under test, and the tile-einsum oracle."""
        def go():
            e, d, ff, t, kk, tm = 8, 2048, 2048, 1024, 2, 256
            x = jax.random.normal(k0, (t, d), dt)
            ids = jax.random.randint(jax.random.PRNGKey(13), (t, kk),
                                     0, e)
            w = jax.random.normal(jax.random.PRNGKey(14), (e, d, ff),
                                  dt) * 0.02
            x_s, te, _ = jax.jit(
                lambda a, b: ops.prepare_grouped_tokens(a, b, e, tm)
            )(x, ids)
            if op == "ag":
                ctx = ops.create_ag_moe_context(
                    mctx, num_experts=e, block_m=tm, block_n=512,
                    block_k=1024)
                f = sm(lambda a, ww, t_: ops.ag_group_gemm(
                    a, ww, t_, ctx, force_kernel=True),
                       (P(None, None), P(None, None, None), P(None)))
            else:
                f = jax.jit(lambda a, ww, t_: ops.grouped_gemm_tiles(
                    a, ww, t_, block_n=512, block_k=1024))
            out = np.asarray(f(x_s, w, te), np.float32)
            tiles = np.asarray(x_s, np.float32).reshape(-1, tm, d)
            want = np.einsum("ima,iaf->imf", tiles,
                             np.asarray(w, np.float32)[np.asarray(te)])
            np.testing.assert_allclose(out, want.reshape(out.shape),
                                       rtol=3e-2, atol=3.0)
        return go

    def run_moe_ar():
        y = jax.random.normal(k0, (128, 8, 2048), dt)
        w = jax.nn.softmax(
            jax.random.normal(jax.random.PRNGKey(17), (128, 8)), -1)
        f = sm(lambda yy, ww: ops.moe_reduce_ar(yy, ww, ctx=mctx,
                                                axis="tp", block_n=512,
                                                force_kernel=True),
               (P(None, None, None), P(None, None)))
        out = np.asarray(f(y, w), np.float32)
        want = np.einsum("tkd,tk->td", np.asarray(y, np.float32),
                         np.asarray(w, np.float32))
        np.testing.assert_allclose(out, want, rtol=3e-2, atol=3e-1)

    def run_a2a_gemm_fused():
        x = jax.random.normal(k0, (1, 1024, 4096), dt)
        f = sm(lambda v, w: ops.a2a_gemm_fused(
            v, w, ops.create_a2a_gemm_context(mctx, "tp", block_m=512,
                                              block_n=512, block_k=1024),
            force_kernel=True),
               (P(None, None, None), P(None, None)))
        out = np.asarray(f(x, b4k), np.float32)
        want = (np.asarray(x, np.float32).reshape(1024, 4096)
                @ np.asarray(b4k, np.float32))
        np.testing.assert_allclose(out, want, rtol=3e-2, atol=3.0)

    def run_sp_ag_attention_fused():
        from triton_dist_tpu.ops import sp_ag_attention_fused
        s, h, kvh, hd = 2048, 16, 8, 128
        q = jax.random.normal(k0, (s, h, hd), dt) * 0.3
        kk = jax.random.normal(jax.random.PRNGKey(11), (s, kvh, hd),
                               dt) * 0.3
        vv = jax.random.normal(jax.random.PRNGKey(12), (s, kvh, hd),
                               dt) * 0.3
        f = sm(lambda a, b, c: sp_ag_attention_fused(
            a, b, c, ctx=mctx, axis="tp", force_kernel=True),
               (P(None, None, None),) * 3, P(None, None, None))
        out = np.asarray(f(q, kk, vv), np.float32)
        assert np.isfinite(out).all()

    def run_ulysses():
        ctx = ops.create_ulysses_fused_context(mctx, axis="tp",
                                               block_m=256, block_n=512)
        wq = ops.group_qkv_columns(
            jax.random.normal(k0, (2048, 32 * 128), dt) * 0.02,
            n=1, num_heads=16, num_kv_heads=8, head_dim=128)
        f = sm(lambda x, w: ops.qkv_gemm_a2a(x, w, ctx),
               (P(None, None), P(None, None, None)),
               P(None, None, None))
        out = f(m1k[:1024, :2048].reshape(1024, 2048), wq)
        assert np.isfinite(np.asarray(out, np.float32)).all()

    def run_paged_decode():
        kp = jax.random.normal(k0, (64, 8, 128, 128), dt) * 0.3
        vp = jax.random.normal(jax.random.PRNGKey(7),
                               (64, 8, 128, 128), dt) * 0.3
        tbl = jnp.arange(64, dtype=jnp.int32).reshape(8, 8)
        kv_len = jnp.full((8,), 777, jnp.int32)
        q = jax.random.normal(jax.random.PRNGKey(8), (8, 32, 128), dt)
        out = jax.jit(lambda q_: ops.paged_flash_decode(
            q_, kp, vp, tbl, kv_len))(q)
        assert np.isfinite(np.asarray(out, np.float32)).all()

    def run_fused_decode():
        """Fused split-KV decode (in-kernel RDMA partial exchange,
        sim_ranks=8 self-exchange at full schedule/traffic) vs the
        pmax+2psum XLA composition — the VERDICT-r4 sim-ranks number
        for the one-kernel-per-step path (reference flash_decode.py
        1→32-GPU scaling)."""
        from triton_dist_tpu.ops import sp_flash_decode_fused
        from triton_dist_tpu.ops.flash_decode import sp_flash_decode

        b, h, kvh, hd, t = 8, 32, 8, 128, 2048
        q = jax.random.normal(k0, (b, h, hd), dt) * 0.3
        k_hm = jax.random.normal(jax.random.PRNGKey(21),
                                 (b, kvh, t, hd), dt) * 0.3
        v_hm = jax.random.normal(jax.random.PRNGKey(22),
                                 (b, kvh, t, hd), dt) * 0.3
        kv_len = jnp.full((b,), t, jnp.int32)

        fused = sm(lambda qq, l: sp_flash_decode_fused(
            qq, k_hm, v_hm, l, ctx=mctx, axis="tp", page=256,
            sim_ranks=8),
            (P(None, None, None), P(None)), P(None, None, None))
        k_tm = jnp.transpose(k_hm, (0, 2, 1, 3))
        v_tm = jnp.transpose(v_hm, (0, 2, 1, 3))
        xla = sm(lambda qq, l: sp_flash_decode(qq, k_tm, v_tm, l,
                                               axis="tp"),
                 (P(None, None, None), P(None)), P(None, None, None))
        got = np.asarray(fused(q, kv_len), np.float32)
        want = np.asarray(xla(q, kv_len), np.float32)
        np.testing.assert_allclose(got, want, rtol=3e-2, atol=3e-2)

        times = _timed_chain_group(
            {"fused": (lambda a, b_: fused(a, kv_len), q, q),
             "xla": (lambda a, b_: xla(a, kv_len), q, q)},
            repeats=3, hi=72)
        cache_gb = 2 * b * kvh * t * hd * 2 / 1e9
        return {"fused_decode_ms": round(times["fused"] * 1e3, 4),
                "xla_decode_ms": round(times["xla"] * 1e3, 4),
                "fused_vs_xla": round(times["xla"]
                                      / max(times["fused"], 1e-9), 4),
                "fused_decode_gbps": round(
                    cache_gb / max(times["fused"], 1e-9), 1)}

    def run_decode_perf():
        """Decode throughput, layer engine vs megakernel, measured as
        the slope between two on-device greedy-decode loop lengths (the
        tunnel RTT cancels) — the reference's ``bench_qwen3.py``
        comparison."""
        from triton_dist_tpu.models import ModelConfig, dense
        from triton_dist_tpu.megakernel.engine import MegaKernelEngine

        cfg = ModelConfig.tiny(
            vocab_size=8192, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=4, head_dim=128)
        B, PRE, LEN = 8, 128, 512
        specs = dense.param_specs(cfg, "tp")
        params = jax.tree.map(
            lambda x, s: jax.device_put(
                x, jax.sharding.NamedSharding(mesh, s)),
            dense.init_params(jax.random.PRNGKey(0), cfg), specs)
        ids = jax.random.randint(jax.random.PRNGKey(1), (B, PRE), 0,
                                 cfg.vocab_size)
        kv_spec = dense.cache_specs("tp")

        prefill = jax.jit(jax.shard_map(
            lambda p, i: dense.prefill(p, i, cfg, max_len=LEN),
            mesh=mesh, in_specs=(specs, P(None, None)),
            out_specs=(P(None, None), kv_spec), check_vma=False))
        logits0, cache0 = prefill(params, ids)
        tok0 = jnp.argmax(logits0, -1).astype(jnp.int32)

        def make_layer_loop(iters):
            def inner(p, tok, cache):
                def body(_, carry):
                    tok, cache = carry
                    lg, cache = dense.decode_step(p, tok, cache, cfg)
                    return (jnp.argmax(lg, -1).astype(jnp.int32), cache)
                tok, cache = jax.lax.fori_loop(0, iters, body,
                                               (tok, cache))
                return tok
            return jax.jit(jax.shard_map(
                inner, mesh=mesh, in_specs=(specs, P(None), kv_spec),
                out_specs=P(None), check_vma=False))

        def slope(make, lo=8, hi=32, reps=3):
            best = {}
            for it in (lo, hi):
                f = make(it)
                f()  # compile + warm
                b = float("inf")
                for _ in range(reps):
                    t0 = time.perf_counter()
                    f()
                    b = min(b, time.perf_counter() - t0)
                best[it] = b
            return (best[hi] - best[lo]) / (hi - lo)

        t_layer = slope(lambda it: (
            lambda f=make_layer_loop(it): np.asarray(
                f(params, tok0, cache0))))

        # Megakernel: same loop over the persistent-kernel step.
        mk = MegaKernelEngine(cfg, mesh, batch=B, max_len=LEN,
                              prefill_seq=PRE)
        mk.prefill(ids)
        step = mk.builder.step_fn()
        kvspec_mk = P(None, None, None, "tp", None)

        def make_mk_loop(iters):
            def inner(arena, k, v, tok, tbl):
                def body(i, carry):
                    tok, arena, k, v = carry
                    lg, arena, k, v = step(arena, k, v, tok, PRE + i,
                                           tbl)
                    return (jnp.argmax(lg, -1).astype(jnp.int32),
                            arena, k, v)
                out = jax.lax.fori_loop(
                    0, iters, body, (tok, arena, k, v))
                return out[0]
            return jax.jit(jax.shard_map(
                inner, mesh=mesh,
                in_specs=(P("tp", None), kvspec_mk, kvspec_mk, P(None),
                          P(None)),
                out_specs=P(None), check_vma=False))

        t_mk = slope(lambda it: (
            lambda f=make_mk_loop(it): np.asarray(
                f(mk._arena, mk.k_cache, mk.v_cache, tok0,
                  mk.block_table))))
        return {"layer_tok_s": round(B / max(t_layer, 1e-9), 1),
                "megakernel_tok_s": round(B / max(t_mk, 1e-9), 1),
                "batch": B, "prefix": PRE,
                # On TPU, jit already compiles the whole layer decode
                # into ONE executable, so the megakernel's
                # launch-elimination win (the reference's GPU story)
                # does not transfer; its persistent task loop pays
                # interpreter overhead instead. Kept as an honest
                # capability measurement.
                "note": "layer decode is one XLA executable under jit"}

    def run_hybrid_gdn():
        from triton_dist_tpu.models import Engine, ModelConfig, qwen_next

        cfg = ModelConfig.tiny_next(
            hidden_size=256, intermediate_size=512,
            num_attention_heads=8, num_key_value_heads=4, head_dim=32,
            gdn_num_heads=8, gdn_head_dim_k=32, gdn_head_dim_v=32)
        eng = Engine(cfg, mesh, mode="xla", max_len=128, seed=7,
                     model=qwen_next)
        ids = jax.random.randint(jax.random.PRNGKey(18), (2, 64), 0,
                                 cfg.vocab_size)
        toks = np.asarray(eng.serve(ids, gen_len=8))
        assert toks.shape == (2, 8) and np.isfinite(toks).all()

    def run_hybrid_hf_cell():
        """HF-checkpoint-faithful Qwen3-Next cell (conv GDN + gated
        attention + shared-expert MoE) through the Engine — the shape
        real checkpoints serve with."""
        from triton_dist_tpu.models import Engine, ModelConfig, qwen_next

        n = len(mesh.devices.reshape(-1))
        cfg = ModelConfig.tiny_next(
            vocab_size=256, hidden_size=256, intermediate_size=512,
            num_hidden_layers=2, num_attention_heads=max(8, n),
            num_key_value_heads=max(8, n), head_dim=32,
            gdn_num_heads=2 * max(8, n), gdn_head_dim_k=32,
            gdn_head_dim_v=32, full_attn_interval=2,
            gdn_num_key_heads=max(8, n), gdn_conv_kernel=4,
            attn_gate=True, partial_rotary_factor=0.25,
            num_experts=8, num_experts_per_tok=2,
            moe_intermediate_size=128,
            shared_expert_intermediate_size=128)
        eng = Engine(cfg, mesh, mode="xla", max_len=128, seed=9,
                     model=qwen_next)
        ids = jax.random.randint(jax.random.PRNGKey(19), (2, 64), 0,
                                 cfg.vocab_size)
        toks = np.asarray(eng.serve(ids, gen_len=8))
        assert toks.shape == (2, 8) and np.isfinite(toks).all()

    def run_megakernel(paged):
        def go():
            from triton_dist_tpu.megakernel.engine import MegaKernelEngine
            from triton_dist_tpu.models.config import ModelConfig

            cfg = ModelConfig.tiny(vocab_size=4096, hidden_size=1024,
                                   intermediate_size=2048,
                                   num_hidden_layers=2,
                                   num_attention_heads=8,
                                   num_key_value_heads=4, head_dim=128)
            eng = MegaKernelEngine(cfg, mesh, batch=4, max_len=256,
                                   prefill_seq=16, paged=paged)
            prompts = jnp.ones((4, 16), jnp.int32)
            logits = eng.prefill(prompts)
            assert np.isfinite(np.asarray(logits, np.float32)).all()
            l2 = eng.decode_step(
                jnp.argmax(logits, -1).astype(jnp.int32), 16)
            assert np.isfinite(np.asarray(l2, np.float32)).all()
        return go

    def _run_megakernel_family(make_cfg):
        """Shared silicon gate for the non-dense megakernel families:
        engine + prefill_chain + greedy steps, with the FINAL LOGITS
        checked for finiteness (greedy int tokens are always finite —
        they cannot catch a NaN lowering)."""
        from triton_dist_tpu.megakernel.engine import MegaKernelEngine
        from triton_dist_tpu.models.config import ModelConfig

        eng = MegaKernelEngine(make_cfg(ModelConfig), mesh, batch=4,
                               max_len=128)
        seed = eng.prefill_chain(jnp.ones((4, 8), jnp.int32))
        tok = seed
        for i in range(4):
            logits = eng.decode_step(tok, 7 + i)
            tok = jnp.argmax(logits, -1).astype(jnp.int32)
        lg = np.asarray(logits, np.float32)
        assert lg.shape[0] == 4 and np.isfinite(lg).all()

    def run_megakernel_moe():
        """MOE_WEIGHTS/WEIGHTED_ADD task bodies on real Mosaic (they
        have interpret-mode coverage; this is their silicon gate)."""
        _run_megakernel_family(lambda MC: MC.tiny_moe(
            vocab_size=4096, hidden_size=1024, num_hidden_layers=2,
            num_attention_heads=8, num_key_value_heads=4, head_dim=128,
            num_experts=8, num_experts_per_tok=2,
            moe_intermediate_size=512))

    def run_megakernel_hybrid():
        """GDN_DECODE task body on real Mosaic (recurrent state buffer
        threading + per-head delta-rule update)."""
        _run_megakernel_family(lambda MC: MC.tiny_next(
            vocab_size=4096, hidden_size=1024, num_hidden_layers=2,
            num_attention_heads=8, num_key_value_heads=4, head_dim=128,
            gdn_num_heads=8, gdn_head_dim_k=128, gdn_head_dim_v=128,
            full_attn_interval=2))

    def run_real_checkpoint_decode():
        """decode_tok_s for a REAL public checkpoint through BOTH
        engines (ROADMAP item 3's missing number). Harness flag:
        ``BENCH_HF_DIR=<local checkpoint dir>`` — when absent (this
        CPU-only container) the entry records the skip reason instead
        of a number, so the next on-chip run captures it by exporting
        one variable. Decode rate is the slope between two generation
        lengths (prefill + tunnel RTT cancel)."""
        hf_dir = os.environ.get("BENCH_HF_DIR")
        if not hf_dir:
            return {"skipped": "set BENCH_HF_DIR=<hf checkpoint dir> "
                               "to record real-checkpoint decode_tok_s"}
        from triton_dist_tpu.models import Engine, qwen_moe
        from triton_dist_tpu.models.hf_loader import load_hf_checkpoint
        from triton_dist_tpu.megakernel.engine import MegaKernelEngine

        cfg, params = load_hf_checkpoint(hf_dir, dtype=jnp.bfloat16)
        b, pre, lo, hi = 4, 32, 8, 64
        ids = jax.random.randint(jax.random.PRNGKey(0), (b, pre), 0,
                                 cfg.vocab_size)
        model_kw = {"model": qwen_moe} if cfg.is_moe else {}
        eng = Engine(cfg, mesh, mode="xla", max_len=pre + hi + 8,
                     params=params, **model_kw)

        def timed_serve(gen):
            np.asarray(eng.serve(ids, gen_len=gen))   # compile + warm
            t0 = time.perf_counter()
            np.asarray(eng.serve(ids, gen_len=gen))
            return time.perf_counter() - t0

        t_layer = (timed_serve(hi) - timed_serve(lo)) / (hi - lo)
        out = {"checkpoint": os.path.basename(os.path.normpath(hf_dir)),
               "decode_tok_s": {"layer": round(b / max(t_layer, 1e-9),
                                               1)}}
        try:
            mk = MegaKernelEngine(cfg, mesh, batch=b,
                                  max_len=pre + hi + 8, params=params,
                                  prefill_seq=pre)
            tok = jnp.argmax(mk.prefill(ids), -1).astype(jnp.int32)
            np.asarray(mk.decode_step(tok, pre))      # compile + warm

            def timed_mk(gen):
                t = tok
                t0 = time.perf_counter()
                for i in range(gen):
                    lg = mk.decode_step(t, pre + 1 + i)
                    t = jnp.argmax(lg, -1).astype(jnp.int32)
                np.asarray(t)
                return time.perf_counter() - t0

            t_mk = (timed_mk(hi) - timed_mk(lo)) / (hi - lo)
            out["decode_tok_s"]["megakernel"] = round(
                b / max(t_mk, 1e-9), 1)
        except Exception as e:  # record the layer number regardless
            out["decode_tok_s"]["megakernel"] = None
            out["megakernel_error"] = (f"{type(e).__name__}: "
                                       f"{str(e)[:160]}")
        return out

    entries = [
        ("gemm_ar", run_gemm_ar),
        ("allreduce_one_shot", run_allreduce("one_shot")),
        ("allreduce_two_shot", run_allreduce("two_shot")),
        ("allreduce_rhd", run_allreduce("recursive")),
        ("allgather_ring", run_allgather("ring")),
        ("allgather_full_mesh", run_allgather("full_mesh")),
        ("all_to_all", run_a2a),
        ("fast_allgather_push", run_fast_allgather),
        ("ll_a2a_int8", run_ll_a2a),
        ("moe_reduce_rs", run_moe_rs),
        ("moe_reduce_ar", run_moe_ar),
        ("ag_group_gemm", run_grouped("ag")),
        ("grouped_gemm_tiles", run_grouped("local")),
        ("a2a_gemm_fused", run_a2a_gemm_fused),
        ("sp_ag_attention_fused", run_sp_ag_attention_fused),
        ("ep_moe_fused", run_ep_fused),
        ("ulysses_qkv_gemm_a2a", run_ulysses),
        ("paged_flash_decode", run_paged_decode),
        ("fused_sp_decode", run_fused_decode),
        ("ll_a2a_steps", run_ll_a2a_steps),
        ("hybrid_gdn_engine", run_hybrid_gdn),
        ("hybrid_hf_cell_engine", run_hybrid_hf_cell),
        ("engine_decode_throughput", run_decode_perf),
        ("megakernel_prefill_decode", run_megakernel(False)),
        ("megakernel_paged", run_megakernel(True)),
        ("megakernel_moe", run_megakernel_moe),
        ("megakernel_hybrid_gdn", run_megakernel_hybrid),
        ("real_checkpoint_decode", run_real_checkpoint_decode),
    ]
    results = []
    for name, fn in entries:
        if deadline is not None and time.perf_counter() > deadline:
            rec = {"op": name, "ok": False, "skipped": True,
                   "error": "battery time budget exhausted"}
            results.append(rec)
            if not quiet:
                print(json.dumps(rec), flush=True)
            continue
        t0 = time.perf_counter()
        extra = None
        try:
            extra = fn()   # optional dict of measured numbers
            ok, err = True, None
        except Exception as e:  # record, keep going
            ok, err = False, f"{type(e).__name__}: {str(e)[:160]}"
        dt_s = time.perf_counter() - t0
        rec = {"op": name, "ok": ok, "wall_s": round(dt_s, 2)}
        if isinstance(extra, dict):
            rec.update(extra)
        if err:
            rec["error"] = err
        results.append(rec)
        if not quiet:
            print(json.dumps(rec), flush=True)
    n_ok = sum(r["ok"] for r in results)
    if not quiet:
        print(json.dumps({"metric": "hardware_battery_pass_rate",
                          "value": round(n_ok / len(results), 4),
                          "unit": "fraction", "vs_baseline": None,
                          "passed": n_ok, "total": len(results)}))
    return results


if __name__ == "__main__":
    if "--all" in sys.argv:
        battery()
    else:
        main()
