"""Streaming serving loop — the reference's megakernel ``model_server.py``
/ chat-demo analogue (``mega_triton_kernel/test/models``), now on the
continuous-batching :class:`~triton_dist_tpu.serving.ServingEngine`.

Reads one prompt of space-separated token ids per line on stdin and
STREAMS the generated ids as they decode (one token per flush — no
more waiting for the full ``--gen-len``). Malformed prompt lines (non-
integer tokens) terminate with a nonzero exit and a diagnostic instead
of a traceback. With ``--hf-dir`` it loads a real local HF checkpoint
(config.json + safetensors) through ``models.hf_loader`` and serves
THAT model (dense or MoE); otherwise a tiny randomly-initialized dense
model. ``--megakernel`` swaps in the persistent-kernel runtime — the
same ServingEngine drives it through the prefill-lane decode batch.

Run: printf '1 2 3\n9 8 7\n' | python examples/chat_server.py --gen-len 8
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=4)
    ap.add_argument("--gen-len", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--slots", type=int, default=2,
                    help="decode-batch width of the serving engine "
                         "(layer path)")
    ap.add_argument("--page", type=int, default=None,
                    help="KV page size (layer path; must divide "
                         "--max-len)")
    ap.add_argument("--hf-dir", default=None,
                    help="local HF checkpoint directory")
    ap.add_argument("--moe-ep", action="store_true",
                    help="serve the tiny MoE model with experts "
                         "sharded over the mesh (EP decode dispatch)")
    ap.add_argument("--transport", default=None,
                    choices=["ar", "ragged", "ll", "ll2d", "auto"],
                    help="EP decode dispatch transport (--moe-ep / MoE "
                         "checkpoints; see docs/serving.md)")
    ap.add_argument("--ep-nodes", type=int, default=1,
                    help="--moe-ep: split the --tp devices into this "
                         "many nodes — a (nodes, tp/nodes) (dp, tp) "
                         "hierarchy whose decode dispatch rides the "
                         "2-hop ll2d transport (docs/serving.md, "
                         "EP-decode hierarchy)")
    ap.add_argument("--replica-slots", type=int, default=0,
                    help="hot-expert replica slots per MoE layer "
                         "(EP decode, transport=ll)")
    ap.add_argument("--disagg", action="store_true",
                    help="disaggregated prefill/decode serving: the "
                         "first half of the tp devices becomes the "
                         "prefill worker, the second half the decode "
                         "worker (one colocated role at --tp 1); "
                         "completed prefills migrate KV pages to the "
                         "decode pool (see docs/serving.md)")
    ap.add_argument("--buckets", default="8,32",
                    help="--disagg/chunked prefill: comma-separated "
                         "chunk-length buckets (the prefill jit cache "
                         "is bounded by their count)")
    ap.add_argument("--attn-impl", default="ref",
                    choices=["ref", "kernel", "flash"],
                    help="layer-path paged attention implementation: "
                         "'ref' gathers dense rows (CPU default, "
                         "token-exact oracle); 'kernel' streams decode "
                         "through the paged flash kernel; 'flash' also "
                         "routes chunked prefill + speculative "
                         "verification through the paged Q-block "
                         "kernel (see docs/serving.md)")
    ap.add_argument("--kv-quant", default="bf16",
                    choices=["bf16", "int8", "fp8"],
                    help="KV pool storage (both lanes): int8/fp8 "
                         "stores pages quantized with per-page scales "
                         "(2-4x capacity, bounded divergence; with "
                         "--megakernel the persistent lane's arena "
                         "pools quantize too; see docs/serving.md)")
    ap.add_argument("--spec", action="store_true",
                    help="speculative decoding (both lanes): n-gram "
                         "self-draft + one K-token verification "
                         "dispatch, token-exact greedy outputs (with "
                         "--megakernel: the Q-block verification "
                         "task)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="--spec: candidates per verification "
                         "dispatch (static K; jit cache stays flat)")
    ap.add_argument("--kv-tiers", action="store_true",
                    help="layer path: arm the tiered KV memory "
                         "hierarchy — cold committed prefix pages "
                         "demote into a host-RAM tier (scored "
                         "eviction) and prefetch back on reuse, and "
                         "park/resume become serving verbs (see "
                         "docs/serving.md, 'KV memory hierarchy')")
    ap.add_argument("--tier-host-pages", type=int, default=64,
                    help="--kv-tiers: host-tier capacity in pool "
                         "pages")
    ap.add_argument("--park-after-idle", type=int, default=0,
                    metavar="TICKS",
                    help="--kv-tiers: once a running request has "
                         "decoded for N consecutive ticks, park it "
                         "(KV offloaded, slot released) and resume "
                         "it on the next tick — the deterministic "
                         "park/resume drill (token streams stay "
                         "bit-identical to an uninterrupted serve; "
                         "scripts/tier_smoke.sh gates on it)")
    ap.add_argument("--fleet", type=int, default=0, metavar="R",
                    help="layer path: serve through a FleetRouter "
                         "over R replicated serving fleets (prefix-"
                         "affinity routing, health feedback, fleet "
                         "failover — docs/serving.md, 'Fleet "
                         "serving'); prefix_reuse is forced on. "
                         "Combine with --kv-tiers for the parked-tier "
                         "cross-fleet failover path")
    ap.add_argument("--kill-fleet-after", type=int, default=0,
                    metavar="N",
                    help="--fleet: once N tokens have been generated, "
                         "kill one live fleet MID-SERVE (reachable — "
                         "running sessions fail over cross-fleet) and "
                         "keep serving; token streams stay "
                         "bit-identical to an unkilled run "
                         "(scripts/fleet_smoke.sh gates on it)")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="snapshot the full serving state (paged "
                         "pools + scales, allocator, queue, counters; "
                         "--megakernel: the arena by schema) here on "
                         "SIGTERM, and RESUME from an existing "
                         "snapshot on startup — restored requests "
                         "finish token-exact mid-stream "
                         "(docs/serving.md, checkpoint/restore)")
    ap.add_argument("--checkpoint-after", type=int, default=0,
                    help="drill flag for the SIGTERM path: checkpoint "
                         "and exit through the same code path after N "
                         "tokens generated this process (deterministic "
                         "— scripts/chaos_smoke.sh uses it)")
    ap.add_argument("--telemetry", default=None,
                    choices=["off", "counters", "spans"],
                    help="serving telemetry level (docs/observability"
                         ".md): counters = latency histograms only "
                         "(default); spans = full per-request span "
                         "timeline. --trace-out implies spans unless "
                         "overridden")
    ap.add_argument("--trace-out", default=None, metavar="DIR",
                    help="dump the merged Perfetto trace (host spans "
                         "+ megakernel slot records + xprof device "
                         "spans) and a metrics.json snapshot into DIR "
                         "on exit and on SIGTERM, and print the "
                         "one-line 'obs:' latency summary")
    ap.add_argument("--slo", action="store_true",
                    help="layer path: arm the multi-tenant SLO "
                         "scheduling layer — per-tenant bounded "
                         "queues, deadline classes, weighted fair "
                         "share, and priority preemption "
                         "(docs/serving.md, 'Multi-tenant SLO "
                         "scheduling'). Prompts carry a tenant via "
                         "an '@NAME ' line prefix or --tenants")
    ap.add_argument("--tenants", type=int, default=0, metavar="N",
                    help="--slo: label stdin prompts with tenants "
                         "t0..t{N-1} round-robin (lines with an "
                         "explicit '@NAME ' prefix keep their own)")
    ap.add_argument("--tenant-quota", action="append", default=[],
                    metavar="NAME=TOKENS",
                    help="--slo: give NAME a decode-token quota "
                         "bucket refilling at TOKENS/s (repeatable; "
                         "an exhausted tenant queues, it is never "
                         "failed)")
    ap.add_argument("--megakernel", action="store_true")
    ap.add_argument("--mk-model", default="dense",
                    choices=["dense", "moe", "hybrid"],
                    help="--megakernel only: which family the one-"
                         "kernel runtime serves")
    ap.add_argument("--mk-chunked", action="store_true",
                    help="--megakernel: admit prompts through the "
                         "bucketed WRITE_KV_CHUNK/ATTN_CHUNK prefill-"
                         "chunk tasks (chunk lengths from --buckets) "
                         "instead of the one-token-per-tick prefill "
                         "lane (see docs/megakernel.md, 'Chunked "
                         "prefill')")
    args = ap.parse_args()

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.tp}")
    import jax
    if os.environ.get("TDT_REAL_TPU") != "1":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import triton_dist_tpu as tdt
    from triton_dist_tpu.models import Engine, ModelConfig, qwen_moe
    from triton_dist_tpu.serving import QueueFullError, ServingEngine

    import jax.numpy as jnp

    if args.hf_dir and args.megakernel:
        sys.exit("--megakernel serves the built-in tiny model only; "
                 "drop one of --hf-dir/--megakernel")
    if args.disagg and (args.megakernel or args.moe_ep
                        or args.transport or args.replica_slots):
        sys.exit("--disagg splits the layer path's dense/HF serving; "
                 "it does not combine with --megakernel or the EP "
                 "decode knobs")
    if args.megakernel and (args.transport or args.replica_slots):
        sys.exit("--transport/--replica-slots route the layer path's "
                 "EP decode dispatch; the megakernel serves experts "
                 "in-kernel (use --moe-ep without --megakernel)")
    if args.megakernel and args.mk_model == "hybrid" and (
            args.kv_quant != "bf16" or args.spec or args.mk_chunked):
        sys.exit("--kv-quant/--spec/--mk-chunked cover the attention "
                 "families; the hybrid GDN recurrent state is neither "
                 "paged nor rewindable (see docs/serving.md)")
    if args.mk_chunked and not args.megakernel:
        sys.exit("--mk-chunked routes the megakernel's prefill-chunk "
                 "tasks; the layer path gets chunked prefill from "
                 "--disagg or ServingEngine(prefill_buckets=...)")
    if args.megakernel and args.attn_impl != "ref":
        sys.exit("--attn-impl routes the layer path's paged "
                 "attention; the megakernel's attention task has its "
                 "own in-arena lane (see docs/serving.md)")
    if args.checkpoint_after and not args.checkpoint_dir:
        sys.exit("--checkpoint-after needs --checkpoint-dir (it is the "
                 "deterministic drill for that snapshot path)")
    if args.megakernel and args.kv_tiers:
        sys.exit("--kv-tiers routes the layer path's paged pool; the "
                 "megakernel's KV lives in its in-kernel arena "
                 "(see docs/serving.md)")
    if args.park_after_idle and not args.kv_tiers:
        sys.exit("--park-after-idle needs --kv-tiers (parking "
                 "offloads into the tier store)")
    if args.kill_fleet_after and args.fleet < 2:
        sys.exit("--kill-fleet-after needs --fleet >= 2 (killing the "
                 "last live fleet has nowhere to fail over to)")
    if args.fleet and (args.megakernel or args.disagg or args.moe_ep
                       or args.transport or args.replica_slots):
        sys.exit("--fleet fronts replicated layer-path ServingEngines;"
                 " it does not combine with --megakernel/--disagg or "
                 "the EP decode knobs")
    if args.fleet and (args.checkpoint_dir or args.trace_out
                       or args.park_after_idle):
        sys.exit("--fleet does not combine with --checkpoint-dir/"
                 "--trace-out/--park-after-idle (those drive one "
                 "engine; the router has scale_to/kill_fleet drills "
                 "instead)")
    if args.slo and args.megakernel:
        sys.exit("--slo arbitrates the layer path's decode slots; the "
                 "megakernel's persistent lane schedules its own "
                 "(see docs/serving.md)")
    if (args.tenants or args.tenant_quota) and not args.slo:
        sys.exit("--tenants/--tenant-quota need --slo (they configure "
                 "the SLO scheduling layer)")
    slo_specs = []
    for q in args.tenant_quota:
        name, sep, tok = q.partition("=")
        if not sep or not name:
            sys.exit(f"--tenant-quota {q!r}: expected NAME=TOKENS")
        try:
            slo_specs.append({"name": name,
                              "decode_quota": float(tok)})
        except ValueError:
            sys.exit(f"--tenant-quota {q!r}: TOKENS must be a number")
    # Layer-path serving knobs shared by every engine construction
    # below: attention impl, quantized KV pools, speculative decode.
    telemetry = args.telemetry or ("spans" if args.trace_out
                                   else "counters")
    serve_kw = dict(kv_dtype=args.kv_quant,
                    attn_impl=args.attn_impl,
                    spec_k=args.spec_k if args.spec else 0,
                    telemetry=telemetry,
                    kv_tiers=({"host_pages": args.tier_host_pages}
                              if args.kv_tiers else None),
                    slo=({"specs": slo_specs} if args.slo else None))
    def build_disagg(cfg, params, model_kw):
        """Two engines over split tp halves (or one colocated role at
        tp=1) sharing ONE weight pytree, wrapped in the disaggregated
        serving engine — chunked prefill + KV page migration."""
        from triton_dist_tpu.serving import DisaggServingEngine

        buckets = tuple(int(b) for b in args.buckets.split(","))
        devs = jax.devices()
        if args.tp >= 2:
            half = args.tp // 2
            pf_mesh = tdt.make_mesh(tp=half, devices=devs[:half])
            dec_mesh = tdt.make_mesh(tp=args.tp - half,
                                     devices=devs[half:args.tp])
        else:
            pf_mesh = dec_mesh = tdt.make_mesh(tp=1, devices=devs[:1])
        kw = dict(mode="xla", max_len=args.max_len, params=params,
                  **model_kw)
        pf_eng = Engine(cfg, pf_mesh, **kw)
        dec_eng = (pf_eng if pf_mesh is dec_mesh
                   else Engine(cfg, dec_mesh, **kw))
        return DisaggServingEngine(
            dec_eng, prefill_engine=pf_eng, num_slots=args.slots,
            page=args.page, prefill_buckets=buckets, **serve_kw)

    if args.fleet and args.hf_dir:
        sys.exit("--fleet serves the built-in tiny dense model "
                 "(replicated fleets share one weight pytree); drop "
                 "one of --fleet/--hf-dir")
    if args.fleet:
        from triton_dist_tpu.serving import FleetRouter

        cfg = ModelConfig.tiny(vocab_size=128)
        mesh = tdt.make_mesh(tp=args.tp, devices=jax.devices()[:args.tp])
        eng = Engine(cfg, mesh, mode="xla", max_len=args.max_len)

        # Every fleet shares the one Engine (weights + prefill jit)
        # but owns its pools, scheduler, and tier store — the
        # replicated-fleet shape. prefix_reuse forced on: the chained
        # content keys are the affinity signal.
        def fleet_factory():
            return ServingEngine(eng, num_slots=args.slots,
                                 page=args.page, prefix_reuse=True,
                                 **serve_kw)

        srv = FleetRouter(fleet_factory, fleets=args.fleet)
    elif args.hf_dir:
        from triton_dist_tpu.models.hf_loader import load_hf_checkpoint

        cfg, params = load_hf_checkpoint(args.hf_dir, dtype=jnp.float32)
        if not cfg.is_moe and (args.moe_ep or args.transport
                               or args.replica_slots):
            sys.exit(f"{args.hf_dir} is not a MoE checkpoint; "
                     "--moe-ep/--transport/--replica-slots need one")
        model_kw = ({"model": qwen_moe} if cfg.is_moe else {})
        if args.disagg:
            srv = build_disagg(cfg, params, model_kw)
        else:
            mesh = tdt.make_mesh(tp=args.tp,
                                 devices=jax.devices()[:args.tp])
            if cfg.is_moe and (args.moe_ep or args.transport
                               or args.replica_slots):
                model_kw.update(moe_impl="ep",
                                ep_transport=args.transport)
            eng = Engine(cfg, mesh, mode="xla", max_len=args.max_len,
                         params=params, **model_kw)
            srv = ServingEngine(eng, num_slots=args.slots,
                                page=args.page,
                                replica_slots=args.replica_slots,
                                **serve_kw)
    elif args.moe_ep or args.transport or args.replica_slots:
        # --transport / --replica-slots imply the EP-MoE tiny model:
        # silently serving the dense model would drop the knobs.
        cfg = ModelConfig.tiny_moe(vocab_size=128, num_experts=8)
        ep_kw = {}
        if args.ep_nodes > 1:
            # Forced (nodes, chips) hierarchy on the host mesh: dp
            # plays the DCN axis, tp the ICI axis — the decode
            # dispatch resolves to the 2-hop ll2d transport.
            if args.tp % args.ep_nodes:
                sys.exit(f"--ep-nodes {args.ep_nodes} must divide "
                         f"--tp {args.tp}")
            mesh = tdt.make_mesh(dp=args.ep_nodes,
                                 tp=args.tp // args.ep_nodes,
                                 devices=jax.devices()[:args.tp])
            ep_kw["ep_axis"] = ("dp", "tp")
        else:
            mesh = tdt.make_mesh(tp=args.tp,
                                 devices=jax.devices()[:args.tp])
        eng = Engine(cfg, mesh, mode="xla", max_len=args.max_len,
                     model=qwen_moe, moe_impl="ep",
                     ep_transport=args.transport, **ep_kw)
        srv = ServingEngine(eng, num_slots=args.slots, page=args.page,
                            replica_slots=args.replica_slots,
                            **serve_kw)
    elif args.megakernel:
        from jax.sharding import Mesh
        from triton_dist_tpu.megakernel.engine import MegaKernelEngine

        if args.mk_model == "moe":
            cfg = ModelConfig.tiny_moe(vocab_size=128, num_experts=8)
        elif args.mk_model == "hybrid":
            cfg = ModelConfig.tiny_next(vocab_size=128,
                                        num_key_value_heads=4,
                                        full_attn_interval=2)
        else:
            cfg = ModelConfig.tiny(vocab_size=128)
        mesh1d = Mesh(np.array(jax.devices()[:args.tp]), ("tp",))
        # One engine for the whole session; the ServingEngine streams
        # prompts through its prefill lane, so slot count = batch.
        # Quantized KV, speculation, and checkpointing all ride the
        # PAGED arena (per-page scales / block-table verification /
        # schema snapshots); the plain run keeps the original dense
        # cache.
        mk_paged = bool(args.kv_quant != "bf16" or args.spec
                        or args.checkpoint_dir or args.mk_chunked)
        mk_buckets = (tuple(int(b) for b in args.buckets.split(","))
                      if args.mk_chunked else None)
        mk_kw = {}
        if mk_paged:
            page = 16
            if args.max_len % page:
                sys.exit(f"--megakernel with serving knobs pages the "
                         f"arena at {page} tokens; --max-len must be "
                         f"a multiple of {page}")
            mk_kw = dict(paged=True, page=page,
                         num_pages=args.tp * (args.max_len // page) + 1,
                         kv_dtype=args.kv_quant,
                         spec_k=args.spec_k if args.spec else 0,
                         prefill_buckets=mk_buckets)
            if args.spec:
                # The scoreboard claims hot verification chains first.
                mk_kw["schedule"] = "dynamic"
        mk = MegaKernelEngine(cfg, mesh1d, batch=args.tp,
                              max_len=args.max_len, tile_w=16,
                              t_tile=16,
                              profile=bool(args.trace_out), **mk_kw)
        srv = ServingEngine(mk, telemetry=telemetry,
                            kv_dtype=args.kv_quant,
                            spec_k=args.spec_k if args.spec else 0,
                            prefill_buckets=mk_buckets)
    elif args.disagg:
        from triton_dist_tpu.models import dense

        cfg = ModelConfig.tiny(vocab_size=128)
        params = dense.init_params(jax.random.PRNGKey(0), cfg)
        srv = build_disagg(cfg, params, {})
    else:
        cfg = ModelConfig.tiny(vocab_size=128)
        mesh = tdt.make_mesh(tp=args.tp, devices=jax.devices()[:args.tp])
        eng = Engine(cfg, mesh, mode="xla", max_len=args.max_len)
        srv = ServingEngine(eng, num_slots=args.slots, page=args.page,
                            **serve_kw)

    # Telemetry dump wiring (--trace-out): ONE trace session covers
    # the whole serve; on exit (and on SIGTERM, alongside the
    # checkpoint path below) the merged Perfetto trace + a
    # metrics.json snapshot land in the session directory and a
    # one-line latency summary prints.
    tracing = {"ctx": None, "sess": None, "dumped": False}
    if args.trace_out:
        ctx = srv.trace("chat", out_dir=args.trace_out)
        tracing["sess"] = ctx.__enter__()
        tracing["ctx"] = ctx

    def _obs_line(st):
        lat = st.get("latency") or {}

        def pct(series, q):
            v = (lat.get(series) or {}).get(q)
            return "n/a" if v is None else f"{v:.1f}ms"

        return (f"obs: ttft_p50={pct('ttft_ms', 'p50')} "
                f"ttft_p99={pct('ttft_ms', 'p99')} "
                f"itl_p50={pct('itl_ms', 'p50')} "
                f"itl_p99={pct('itl_ms', 'p99')} "
                f"telemetry={st.get('telemetry')}")

    def _dump_obs():
        if tracing["dumped"]:
            return
        tracing["dumped"] = True
        st = srv.stats()
        if tracing["ctx"] is not None:
            tracing["ctx"].__exit__(None, None, None)
            sess = tracing["sess"]
            merged = sess.export()
            metrics = sess.export_metrics(st)
            print(f"trace: merged={merged} metrics={metrics}",
                  flush=True)
        # The obs: line is opt-in (--trace-out / --telemetry): default
        # runs keep their pre-existing stdout contract.
        if ((args.trace_out or args.telemetry)
                and st.get("latency") is not None):
            print(_obs_line(st), flush=True)

    # Checkpoint/restore wiring (layer path): a SIGTERM mid-serve
    # snapshots the full serving state between ticks; a restart with
    # the same flags resumes every in-flight request token-exact.
    ckpt_path = None
    stop = {"flag": False, "serving": False}

    def _snapshot_and_exit():
        from triton_dist_tpu.serving.server import save_checkpoint

        save_checkpoint(srv.checkpoint(), ckpt_path)
        inflight = len(srv.sched.queue) + len(srv.sched.slots)
        print(f"\ncheckpointed {inflight} in-flight "
              f"request(s) to {ckpt_path}", flush=True)
        _dump_obs()
        sys.exit(0)

    if args.checkpoint_dir or args.trace_out:
        import signal

        def _on_term(signum, frame):
            # Mid-serve: only set the flag — the snapshot/dump happens
            # at the next tick boundary where the state is consistent.
            # Idle (blocked on stdin): the engine IS at a boundary, so
            # act right here — otherwise Python's EINTR retry resumes
            # the readline and the signal is swallowed.
            stop["flag"] = True
            if not stop["serving"]:
                if ckpt_path:
                    _snapshot_and_exit()
                _dump_obs()
                sys.exit(0)

        if args.checkpoint_dir:
            os.makedirs(args.checkpoint_dir, exist_ok=True)
            ckpt_path = os.path.join(args.checkpoint_dir,
                                     "serving.ckpt")
        signal.signal(signal.SIGTERM, _on_term)

    def _checkpoint_tick():
        if not (ckpt_path or stop["flag"]):
            return
        done_here = (srv.stats_counters["tokens_generated"]
                     - tokens_at_start)
        if ckpt_path and (stop["flag"] or (
                args.checkpoint_after
                and done_here >= args.checkpoint_after)):
            _snapshot_and_exit()
        elif stop["flag"]:
            # --trace-out without a checkpoint dir: SIGTERM still
            # drains the telemetry at the tick boundary.
            _dump_obs()
            sys.exit(0)

    # --park-after-idle drill: a running request that has decoded for
    # N consecutive ticks parks (KV offloaded wholesale, slot free)
    # and resumes on the next tick — once per request, so the stream
    # always finishes. Token output is bit-identical to an
    # uninterrupted serve (the tier_smoke gate).
    park_state = {"age": {}, "done": set()}

    def _park_tick():
        if not args.park_after_idle:
            return
        for h in list(srv.sched.running()):
            rid = h.request.request_id
            if (h.status != "running" or not h.tokens
                    or rid in park_state["done"]):
                continue
            age = park_state["age"].get(rid, 0) + 1
            park_state["age"][rid] = age
            if age >= args.park_after_idle:
                try:
                    srv.park(h)
                except Exception as e:  # noqa: BLE001 — drill only
                    print(f"[park skipped: {e}]", file=sys.stderr,
                          flush=True)
                    park_state["done"].add(rid)
                    continue
                park_state["done"].add(rid)
                srv.resume(h)

    # --kill-fleet-after drill: once N tokens have streamed, one live
    # fleet dies MID-SERVE (reachable: running sessions park into its
    # tier and hop to a survivor — or re-prefill without tiers). Fires
    # once; streams stay bit-identical to an unkilled run.
    fleet_kill = {"done": False}

    def _fleet_tick():
        if not args.kill_fleet_after or fleet_kill["done"]:
            return
        done_tokens = sum(f.engine.stats_counters["tokens_generated"]
                          for f in srv.fleets)
        if done_tokens < args.kill_fleet_after:
            return
        live = srv._live_fleets()
        if len(live) < 2:
            fleet_kill["done"] = True
            return
        # Prefer a fleet with live work so the kill actually
        # exercises the cross-fleet failover path.
        victim = next((f for f in live if f.engine.sched.slots),
                      live[-1])
        srv.kill_fleet(victim.id, reachable=True)
        fleet_kill["done"] = True
        print(f"[fleet {victim.id} killed mid-serve: failed over]",
              file=sys.stderr, flush=True)

    def run_serving():
        stop["serving"] = True
        try:
            srv.run(on_tick=lambda: (_park_tick(), _checkpoint_tick(),
                                     _fleet_tick()))
        finally:
            stop["serving"] = False

    restored_handles = []
    if ckpt_path and os.path.exists(ckpt_path):
        from triton_dist_tpu.serving.server import load_checkpoint

        restored_handles = srv.restore(load_checkpoint(ckpt_path))
        os.remove(ckpt_path)   # consumed; SIGTERM writes a fresh one
        print(f"restored {len(restored_handles)} in-flight "
              f"request(s) from {ckpt_path}", flush=True)
    tokens_at_start = (srv.stats_counters["tokens_generated"]
                       if hasattr(srv, "stats_counters") else 0)
    if restored_handles:
        run_serving()
        for h in restored_handles:
            # FULL token list (pre-kill + post-restore) — the
            # token-exactness gate diffs this against a clean run.
            print(f"[restored {h.request.request_id}] "
                  + " ".join(str(t) for t in h.tokens), flush=True)

    print(f"serving {cfg.model_name} (vocab {cfg.vocab_size}); one "
          "prompt of space-separated token ids per line:", flush=True)
    n_prompts = 0
    for lineno, line in enumerate(sys.stdin, 1):
        parts = line.split()
        if not parts:
            continue
        # '@NAME ' prefix routes the prompt to that tenant (--slo);
        # otherwise --tenants N labels prompts t0..t{N-1} round-robin.
        tenant = None
        if parts[0].startswith("@") and len(parts[0]) > 1:
            tenant = parts[0][1:]
            parts = parts[1:]
            if not parts:
                continue
        elif args.tenants:
            tenant = f"t{n_prompts % args.tenants}"
        n_prompts += 1
        try:
            ids = [int(t) % cfg.vocab_size for t in parts]
        except ValueError as e:
            print(f"error: line {lineno} is not space-separated token "
                  f"ids ({e})", file=sys.stderr, flush=True)
            sys.exit(2)

        print("->", end="", flush=True)

        def stream(tok, handle):
            print(f" {tok}", end="", flush=True)

        try:
            srv.submit(ids, max_new_tokens=args.gen_len,
                       stream_cb=stream, tenant=tenant)
        except (ValueError, QueueFullError) as e:
            # Too long for the configured capacity (or a tenant's own
            # backpressure): skip the request, keep the server alive
            # (old behaviour, same message spot).
            print(f" [skipped: {e}]", flush=True)
            continue
        run_serving()
        print(flush=True)

    # One-line serving summary on exit — the load data used to be
    # collected and silently dropped.
    st = srv.stats()
    line = (f"served {st['completed']} request(s), "
            f"{st['tokens_generated']} tokens, "
            f"{st['decode_dispatches']} decode dispatches")
    if st.get("dispatch_transport"):
        line += f", transport={st['dispatch_transport']}"
    if st.get("prefill_buckets"):
        line += (f", prefill_chunks={st['prefill_chunks']} "
                 f"(buckets {st['prefill_buckets']}, "
                 f"jit entries {st['prefill_cache_size']})")
    if st.get("migration_transport"):
        line += (f", roles={st['roles']}, "
                 f"migration={st['migration_transport']}, "
                 f"migrated_pages={st['migrated_pages']}")
    if st.get("attn_impl") not in (None, "ref") or st.get(
            "chunk_attn") not in (None, "ref"):
        line += (f", attn={st['attn_impl']}"
                 f" (chunk/verify {st['chunk_attn']})")
    if st.get("kv_dtype") not in (None, "bf16"):
        line += (f", kv_dtype={st['kv_dtype']} "
                 f"({st['kv_bytes_per_token']:.0f} B/token)")
    if args.megakernel:
        # Lane-capability line: smoke scripts gate on this instead of
        # grepping tracebacks for the old layer-path-only rejects.
        line += (f", mk: kv_dtype={st['mk_kv_dtype']} "
                 f"spec={st['mk_spec']} checkpointable="
                 f"{'yes' if st['mk_checkpointable'] else 'no'} "
                 f"chunked={st['mk_chunked_prefill'] or 'no'}")
    if args.kv_tiers:
        rate = st.get("kv_hot_hit_rate")
        line += (f", tiers: offloaded={st['offloaded_pages']} "
                 f"resumed={st['resumes']} "
                 f"hit-rate={'n/a' if rate is None else f'{rate:.2f}'}"
                 f" (tier_pages={st['tier_pages']} "
                 f"parked={st['parked_sessions']})")
    if args.fleet:
        ar = st.get("router_affinity_hit_rate")
        line += (f", fleet: routed={st['routed']} "
                 f"failovers={st['fleet_failovers']} "
                 f"(resumed={st['failover_resumed']} "
                 f"reprefilled={st['failover_reprefilled']}) "
                 f"shed={st['shed_requests']} "
                 f"affinity-hit-rate="
                 f"{'n/a' if ar is None else f'{ar:.2f}'} "
                 f"live={st['live_fleets']}/{len(srv.fleets)}")
    if args.slo:
        at = st.get("slo_attainment")
        tn = (st.get("slo") or {}).get("tenants") or {}
        per_lat = ((st.get("latency") or {}).get("per_tenant")
                   or {})
        line += (f", slo: attainment="
                 f"{'n/a' if at is None else f'{at:.2f}'} "
                 f"preemptions={st['slo_preemptions']} "
                 f"tenants={len(tn)}")
        for name in sorted(tn):
            t = tn[name]
            p99 = ((per_lat.get(name) or {}).get("ttft_ms")
                   or {}).get("p99")
            line += (f" {name}(released={t['released']} "
                     f"preempted={t['preempted']} p99-ttft="
                     f"{'n/a' if p99 is None else f'{p99:.0f}ms'})")
    if (st["retries"] or st["failovers"] or st["restored_requests"]
            or args.checkpoint_dir):
        line += (f", ft: retries={st['retries']} "
                 f"failovers={st['failovers']} "
                 f"restored={st['restored_requests']}")
    if st.get("spec"):
        sp = st["spec"]
        rate = sp["accept_rate"]
        line += (f", spec k={sp['k']} "
                 f"(accept={'n/a' if rate is None else f'{rate:.2f}'}, "
                 f"{sp['tokens_per_dispatch']:.2f} tok/dispatch)")
    if st.get("expert_load") is not None:
        load = st["expert_load"]
        hot = max(range(len(load)), key=load.__getitem__)
        tot = st["expert_totals"]
        share = tot[hot] / max(sum(tot), 1)
        line += (f"; expert-load: hot=e{hot} "
                 f"({share:.2f} of routed traffic), "
                 f"totals={tot}")
        if st.get("replicated_experts"):
            line += (", replicas=" + ",".join(
                f"e{e}->r{r}"
                for e, r in sorted(st["replicated_experts"].items())))
    print(line, flush=True)
    _dump_obs()


if __name__ == "__main__":
    main()
