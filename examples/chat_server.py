"""Minimal serving loop — the reference's megakernel ``model_server.py``
/ chat-demo analogue (``mega_triton_kernel/test/models``).

Reads one prompt of space-separated token ids per line on stdin, greedy-
decodes, prints the generated ids. With ``--hf-dir`` it loads a real
local HF checkpoint (config.json + safetensors) through
``models.hf_loader.load_hf_checkpoint`` and serves THAT model (dense or
MoE — the Engine picks the MoE contract from the config); otherwise a
tiny randomly-initialized dense model. ``--megakernel`` swaps the layer
engine for the persistent-kernel runtime.

Run: printf '1 2 3\n9 8 7\n' | python examples/chat_server.py --gen-len 8
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=4)
    ap.add_argument("--gen-len", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--hf-dir", default=None,
                    help="local HF checkpoint directory")
    ap.add_argument("--megakernel", action="store_true")
    ap.add_argument("--mk-model", default="dense",
                    choices=["dense", "moe", "hybrid"],
                    help="--megakernel only: which family the one-"
                         "kernel runtime serves")
    args = ap.parse_args()

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.tp}")
    import jax
    if os.environ.get("TDT_REAL_TPU") != "1":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import triton_dist_tpu as tdt
    from triton_dist_tpu.models import Engine, ModelConfig, qwen_moe

    if args.hf_dir and args.megakernel:
        sys.exit("--megakernel serves the built-in tiny model only; "
                 "drop one of --hf-dir/--megakernel")
    mesh = tdt.make_mesh(tp=args.tp, devices=jax.devices()[:args.tp])
    mk = None
    if args.hf_dir:
        from triton_dist_tpu.models.hf_loader import load_hf_checkpoint

        cfg, params = load_hf_checkpoint(args.hf_dir, dtype=jnp.float32)
        model_kw = ({"model": qwen_moe} if cfg.is_moe else {})
        eng = Engine(cfg, mesh, mode="xla", max_len=args.max_len,
                     params=params, **model_kw)
    elif args.megakernel:
        from jax.sharding import Mesh
        from triton_dist_tpu.megakernel.engine import MegaKernelEngine

        if args.mk_model == "moe":
            cfg = ModelConfig.tiny_moe(vocab_size=128, num_experts=8)
        elif args.mk_model == "hybrid":
            cfg = ModelConfig.tiny_next(vocab_size=128,
                                        num_key_value_heads=4,
                                        full_attn_interval=2)
        else:
            cfg = ModelConfig.tiny(vocab_size=128)
        mesh1d = Mesh(np.array(jax.devices()[:args.tp]), ("tp",))
        # One engine for the whole session: construction/jit are
        # prompt-length independent (prefill_chain is length-agnostic).
        mk = MegaKernelEngine(cfg, mesh1d, batch=args.tp,
                              max_len=args.max_len, tile_w=16, t_tile=16)
        eng = None
    else:
        cfg = ModelConfig.tiny(vocab_size=128)
        eng = Engine(cfg, mesh, mode="xla", max_len=args.max_len)

    print(f"serving {cfg.model_name} (vocab {cfg.vocab_size}); one "
          "prompt of space-separated token ids per line:", flush=True)
    for line in sys.stdin:
        ids = [int(t) % cfg.vocab_size for t in line.split()]
        if not ids:
            continue
        if len(ids) + args.gen_len > args.max_len:
            print(f"-> [skipped: prompt {len(ids)} + gen {args.gen_len} "
                  f"exceeds --max-len {args.max_len}]", flush=True)
            continue
        # Token-sharded prefill needs B*S divisible by tp; serving
        # B=tp copies of the prompt satisfies it for ANY length (the
        # rows are identical; row 0 is the answer).
        prompt = jnp.asarray(np.tile(np.array([ids], np.int32),
                                     (args.tp, 1)))
        if args.megakernel:
            # Fresh recurrent state per prompt (hybrid family): stale
            # KV is masked by cache_len, stale GDN state is not.
            mk.reset_states()
            seed = mk.prefill_chain(prompt)
            toks = np.asarray(mk.generate(seed, steps=args.gen_len,
                                          start_pos=len(ids) - 1))
        else:
            toks = np.asarray(eng.serve(prompt, gen_len=args.gen_len))
        print("->", " ".join(str(t) for t in toks[0].tolist()),
              flush=True)


if __name__ == "__main__":
    main()
