"""Batch-serving demo for the dense Qwen3 engine.

Reference analogue: ``test_e2e_inference.py`` / the megakernel
``model_server.py`` chat demo. Runs greedy generation over a token
batch and reports per-token latency; add ``--megakernel`` to run every
decode step as one persistent Pallas kernel per device.

Run (CPU mesh): python examples/serve_dense.py
Run (real TPUs): TDT_REAL_TPU=1 python examples/serve_dense.py --tp 8
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=8)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--prompt-len", type=int, default=16)
    ap.add_argument("--gen-len", type=int, default=16)
    ap.add_argument("--mode", default="fused",
                    choices=["xla", "fused", "fused_ar"])
    ap.add_argument("--megakernel", action="store_true")
    ap.add_argument("--paged", action="store_true",
                    help="megakernel paged-KV cache (page pool + block "
                         "table) instead of the dense cache")
    ap.add_argument("--model", default="dense",
                    choices=["dense", "qwen_moe"])
    ap.add_argument("--moe-impl", default="tp", choices=["tp", "ep"],
                    help="qwen_moe only: TP experts (ffn-sharded) or EP "
                         "experts (dispatch/combine all-to-all)")
    args = ap.parse_args()

    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + f" --xla_force_host_platform_device_count={args.tp}")
    import jax
    if os.environ.get("TDT_REAL_TPU") != "1":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import triton_dist_tpu as tdt
    from triton_dist_tpu.models import ModelConfig, Engine

    # vocab kept small so the megakernel arena stays under the CPU
    # interpret-mode per-buffer limit (docs/testing.md).
    if args.model == "qwen_moe":
        cfg = ModelConfig.tiny_moe(vocab_size=64, num_experts=8)
    else:
        cfg = ModelConfig.tiny(vocab_size=64)
    mesh = tdt.make_mesh(tp=args.tp)
    ids = jax.random.randint(jax.random.PRNGKey(0),
                             (args.batch, args.prompt_len), 0,
                             cfg.vocab_size)

    if args.megakernel:
        from jax.sharding import Mesh
        from triton_dist_tpu.megakernel.engine import MegaKernelEngine

        mesh1d = Mesh(np.array(jax.devices()[:args.tp]), ("tp",))
        max_len = -(-(args.prompt_len + args.gen_len) // 16) * 16
        eng = MegaKernelEngine(cfg, mesh1d, batch=args.batch,
                               max_len=max_len, tile_w=16, t_tile=16,
                               paged=args.paged)
        t0 = time.perf_counter()
        seed = eng.prefill_chain(ids)
        toks = np.asarray(eng.generate(seed, steps=args.gen_len,
                                       start_pos=args.prompt_len - 1))
        dt = time.perf_counter() - t0
    else:
        extra, mode = {}, args.mode
        if args.model == "qwen_moe":
            from triton_dist_tpu.models import qwen_moe

            # MoE serve runs the XLA collectives; the fused MoE blocks
            # are exercised by forward_tokens/tests at these tiny shapes.
            extra = {"model": qwen_moe, "moe_impl": args.moe_impl}
            if args.mode != "xla":
                print(f"note: --model qwen_moe serves in mode=xla "
                      f"(requested --mode {args.mode} applies to the "
                      "dense model only)")
            mode = "xla"
        eng = Engine(cfg, mesh, mode=mode,
                     max_len=args.prompt_len + args.gen_len,
                     block_m=8, block_n=8, block_k=32, **extra)
        t0 = time.perf_counter()
        toks = np.asarray(eng.serve(ids, gen_len=args.gen_len))
        dt = time.perf_counter() - t0

    print("generated tokens:\n", toks)
    print(f"{toks.size} tokens in {dt:.2f}s "
          f"({dt / max(toks.shape[1], 1) * 1e3:.1f} ms/step incl. "
          "interpret overhead)" if os.environ.get("TDT_REAL_TPU") != "1"
          else f"{dt / toks.shape[1] * 1e3:.2f} ms/step")


if __name__ == "__main__":
    main()
